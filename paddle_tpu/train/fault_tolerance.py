"""Preemption-safe fault-tolerant training (docs/ROBUSTNESS.md).

On preemptible fleets the dominant training failure is not a bug — it is
the machine going away: SIGTERM with a grace window (pod eviction, spot
reclaim), SIGKILL with none, or a single non-finite step poisoning every
weight after it. `CheckpointManager` wraps a `ScanTrainStep` with the
three legs that survive all of them:

**Durable checkpoints.** Each checkpoint is a `save_sharded` directory
``<root>/step-<n>`` with per-shard content checksums (verified on load —
`distributed/checkpoint.py`), plus a ``COMPLETE`` marker and an atomic
``LATEST`` pointer written ONLY after every shard and index has landed: a
checkpoint is either complete or invisible, so a crash at any byte
boundary can never publish garbage. Retention keeps the newest
``keep`` complete checkpoints, never touching the one currently being
resumed from or written. Async saves block the step loop only for the
host snapshot (`async_save` copies device state synchronously, the write
overlaps the next donated steps); a failed background write surfaces on
the next `wait()`/`save()`, never vanishes in a daemon thread.

**Preemption + resume.** `maybe_save` checkpoints every ``every``
optimizer steps; `install_sigterm` turns SIGTERM into "finish the current
step, synchronous checkpoint, clean exit" (the training mirror of serve's
`install_sigterm_drain`); `restore` reloads params, ZeRO-1 dp-sharded
optimizer state, the optimizer step clock, the PRNG key chain, and the
data cursor — bit-identically on a single replica, to float-ulp across a
mesh reshard (the load adopts the CURRENT step's shardings, so resuming
under a different dp/mp/sp plan needs no conversion step).

**Bad-step containment.** The donated program already skips the optimizer
apply on any non-finite loss/grad (`ScanTrainStep`, zero recompiles);
`after_step` adds the ladder: count `train.bad_steps`, and after
``max_consecutive_bad`` in a row roll back to the last checkpoint and
raise a typed `TooManyBadSteps` instead of training on garbage.

Everything is counted (`train.checkpoint_seconds`, `train.checkpoints`,
`train.resumes`, `train.bad_steps`, `train.rollbacks` —
docs/OBSERVABILITY.md) and flight-recorded. Chaos coverage:
tests/test_train_chaos.py drives the `ckpt.*`/`train.step_nan` fault
sites (`testing/faults.py`) plus real SIGTERM/SIGKILL subprocess drills.
"""
from __future__ import annotations

import os
import re
import shutil
import signal
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.distributed.checkpoint import (CheckpointCorrupt,
                                               CheckpointIncomplete,
                                               async_save, load_sharded,
                                               save_sharded)
from paddle_tpu.distributed.liveness import PeerLost
from paddle_tpu.observability import metrics
from paddle_tpu.observability.flight_recorder import flight
from paddle_tpu.testing import faults

__all__ = ["CheckpointManager", "TooManyBadSteps", "CheckpointCorrupt",
           "CheckpointIncomplete", "PeerLost"]

# `step-<n>` plus optional rewrite generation `-r<k>`: re-saving at an
# unchanged step number (resume -> cursor-only advance -> finalize) writes
# a FRESH dir instead of degrading the live one, so the old checkpoint
# keeps its COMPLETE marker until the replacement is published
_DIR_RE = re.compile(r"^step-(\d{8})(?:-r\d+)?$")


class TooManyBadSteps(RuntimeError):
    """``max_consecutive_bad`` steps in a row produced non-finite
    loss/grads. The manager has already rolled the training state back to
    the last complete checkpoint (when one exists) — the raiser's job is
    to stop the loop loudly: whatever is producing NaNs (data corruption,
    an lr spike, broken hardware) will not fix itself by iterating."""


class CheckpointManager:
    """Drives preemption-safe checkpointing for one `ScanTrainStep`.

    root                : directory holding ``step-<n>`` checkpoints + LATEST
    step                : the ScanTrainStep (or `bind()` later — hapi route)
    every               : checkpoint every N optimizer steps (0 = only
                          explicit `save()` calls)
    keep                : retention — newest N complete checkpoints survive
    max_consecutive_bad : bad-step ladder threshold (0 disables rollback)
    use_async           : background writes by default; `save(sync=True)`,
                          the SIGTERM path, and EVERY multihost save
                          force synchronous
    world               : (rank, size) — auto-detected from the launch
                          env / jax runtime. size > 1 turns on the fleet
                          publication protocol (key-partitioned shard
                          writes, pre-COMPLETE barrier, rank-0 publish;
                          docs/ROBUSTNESS.md "Multi-host training");
                          root must then be a SHARED filesystem
    barrier             : injectable rendezvous ``fn(tag)`` (tests);
                          None = the coordination-service KV barrier
    barrier_timeout_s   : barrier wait bound — past it the save raises
                          typed PeerLost and stays invisible
    """

    def __init__(self, root, step=None, *, every=0, keep=3,
                 max_consecutive_bad=3, use_async=True, world=None,
                 barrier=None, barrier_timeout_s=120.0):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._step = step
        self.every = int(every)
        self.keep = max(1, int(keep))
        self.max_consecutive_bad = int(max_consecutive_bad)
        self.use_async = bool(use_async)
        # multi-host publication (docs/ROBUSTNESS.md "Multi-host
        # training"): world=(rank, size) — auto-detected from the launch
        # env / jax runtime. Each rank writes its key-partition of the
        # state (distributed/checkpoint.py shard_owner); a pre-COMPLETE
        # barrier over the coordination-service KV orders every rank's
        # shards BEFORE rank 0 publishes COMPLETE -> LATEST, so "complete
        # or invisible" holds fleet-wide: a rank that dies mid-save stalls
        # the barrier, which resolves as typed PeerLost on every survivor
        # and the checkpoint stays invisible. The root must be a shared
        # filesystem (the same constraint as the registry's NodeRegistry).
        if world is None:
            rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                      jax.process_index()))
            size = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                      jax.process_count()))
            world = (rank, size)
        self._rank, self._world_size = int(world[0]), int(world[1])
        self._barrier_fn = barrier          # injectable (tests); None = KV
        self.barrier_timeout_s = float(barrier_timeout_s)
        self._save_seq = 0                  # lockstep save counter (dir
        #                                     rendezvous key sequencing)
        self._kv_garbage = []               # superseded barrier tags / dir
        #                                     keys, cleaned after the NEXT
        #                                     save's first barrier
        self._lock = threading.Lock()   # LATEST/prune vs writer thread
        self._pending = None            # (thread, dir) of in-flight async
        self._stop = threading.Event()
        self._resumed_from = None       # never pruned while we depend on it
        self._last_saved = -1

    @property
    def multihost(self):
        return self._world_size > 1

    def bind(self, step):
        """Attach the ScanTrainStep (hapi's Model.fit creates the step
        itself, so its manager is constructed unbound)."""
        self._step = step
        return self

    # ---------------------------------------------------- fleet rendezvous
    def _barrier(self, tag):
        """One fleet rendezvous (multihost only): every rank arrives or
        the wait resolves as typed PeerLost — a barrier that cannot
        complete means a peer died between its shard writes and
        publication, and the checkpoint must stay invisible. The
        ``ckpt.barrier_timeout`` chaos site forces exactly that outcome
        deterministically."""
        if faults.ENABLED and faults.fire("ckpt.barrier_timeout"):
            metrics.counter("train.peer_lost").inc()
            raise PeerLost(
                f"checkpoint barrier {tag!r} timed out (injected via "
                "ckpt.barrier_timeout) — a peer never arrived; the "
                "checkpoint stays unpublished")
        t0 = time.perf_counter()
        try:
            if self._barrier_fn is not None:
                self._barrier_fn(tag)
            else:
                from paddle_tpu.distributed import liveness
                from paddle_tpu.distributed.collective import _kv_client
                liveness.kv_barrier(
                    _kv_client(), tag, rank=self._rank,
                    world=self._world_size,
                    timeout_ms=int(self.barrier_timeout_s * 1e3))
        except PeerLost:
            raise
        except Exception as e:  # noqa: BLE001 — classify timeout as typed
            from paddle_tpu.distributed.liveness import is_timeout
            if is_timeout(e):
                metrics.counter("train.peer_lost").inc()
                raise PeerLost(
                    f"checkpoint barrier {tag!r} timed out after "
                    f"{self.barrier_timeout_s}s — a peer never arrived "
                    f"({e})") from e
            raise
        metrics.histogram("train.barrier_seconds").observe(
            time.perf_counter() - t0)

    def _drain_kv_garbage(self):
        """Rank 0 deletes KV keys from the PREVIOUS save — provably
        unread once the current save's first barrier has completed (see
        liveness.kv_barrier's deferral contract)."""
        if self._rank != 0 or self._barrier_fn is not None:
            return
        with self._lock:
            garbage, self._kv_garbage = list(self._kv_garbage), []
        from paddle_tpu.distributed import liveness
        from paddle_tpu.distributed.collective import _kv_client
        client = _kv_client()
        for kind, val in garbage:
            if kind == "bar":
                liveness.kv_barrier_cleanup(client, val)
            else:
                liveness.clear_with_marker(client, val)

    # ------------------------------------------------------------ directory
    def _dir(self, n):
        return os.path.join(self.root, f"step-{n:08d}")

    @staticmethod
    def _step_of(name):
        m = _DIR_RE.match(os.path.basename(name.rstrip("/")))
        return int(m.group(1)) if m else None

    def _is_complete(self, path):
        return os.path.exists(os.path.join(path, "COMPLETE"))

    def complete_checkpoints(self):
        """Sorted [(step, path)] of COMPLETE checkpoints under root."""
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for name in names:
            n = self._step_of(name)
            p = os.path.join(self.root, name)
            if n is not None and self._is_complete(p):
                out.append((n, p))
        return sorted(out)

    def latest(self):
        """(step, path) of the checkpoint LATEST points to, or None. A
        LATEST naming a non-complete dir (a crash mid-rewrite) falls back
        to the newest complete checkpoint instead of failing the resume."""
        lat = os.path.join(self.root, "LATEST")
        try:
            with open(lat) as f:
                name = f.read().strip()
        except FileNotFoundError:
            name = None
        if name:
            p = os.path.join(self.root, name)
            n = self._step_of(name)
            if n is not None and self._is_complete(p):
                return n, p
        done = self.complete_checkpoints()
        return done[-1] if done else None

    # ----------------------------------------------------------------- save
    def _state(self, data_cursor):
        import json as _json
        from paddle_tpu.optimizer.lr import LRScheduler
        s = self._step
        if s is None:
            raise RuntimeError("CheckpointManager has no ScanTrainStep — "
                               "construct with step= or call bind()")
        meta = {"global_step": int(s.opt._global_step),
                "microbatches": int(s.microbatches),
                "rng": np.asarray(jax.random.key_data(s._key))}
        if isinstance(s.opt._learning_rate, LRScheduler):
            # the schedule position is training state too: resuming a
            # warmup/decay schedule from epoch 0 would be a silently
            # wrong lr for the rest of the run
            meta["lr_sched"] = _json.dumps(
                s.opt._learning_rate.state_dict())
        if data_cursor is not None:
            meta["data_cursor"] = data_cursor
        return {"params": s._params, "opt": s._opt_state, "meta": meta}

    def _publish(self, path):
        """COMPLETE marker + atomic LATEST move-forward + retention — the
        single-writer half of publication (rank 0 in a fleet)."""
        with open(os.path.join(path, "COMPLETE"), "w") as f:
            f.write("ok\n")
        n = self._step_of(path)
        with self._lock:
            cur = self.latest()
            if cur is None or n >= cur[0]:
                tmp = os.path.join(self.root, "LATEST.tmp")
                with open(tmp, "w") as f:
                    f.write(os.path.basename(path) + "\n")
                os.replace(tmp, os.path.join(self.root, "LATEST"))
            self._prune(protect=path)

    def _finalize(self, path):
        """Publish a fully-written checkpoint: COMPLETE marker, atomic
        LATEST move-forward, retention. Runs on the WRITER thread for
        async saves — everything here happens after the last shard byte
        landed, which is the whole crash-consistency protocol.

        Multihost: a pre-COMPLETE barrier orders EVERY rank's shards
        before rank 0 publishes, and a post-publication barrier keeps any
        rank from racing ahead of the visible LATEST — either barrier
        failing (a dead peer, ``ckpt.barrier_timeout``) raises typed
        PeerLost with the checkpoint still invisible."""
        base = os.path.basename(path)
        if self.multihost:
            self._barrier(f"{base}/shards")
            # every rank is past the previous save's barriers now — its
            # KV keys are provably unread and safe to delete
            self._drain_kv_garbage()
            if self._rank == 0:
                self._publish(path)
            self._barrier(f"{base}/pub")
            if self._rank == 0:
                # only rank 0 drains the list — other ranks appending
                # would just grow dead weight forever
                with self._lock:
                    self._kv_garbage += [("bar", f"{base}/shards"),
                                         ("bar", f"{base}/pub")]
        else:
            self._publish(path)
        n = self._step_of(path)
        metrics.counter("train.checkpoints").inc()
        flight.record("train.checkpoint_complete", step=n, path=base,
                      rank=self._rank)

    def _prune(self, protect=None):
        """Keep the newest ``keep`` COMPLETE checkpoints. Never removes the
        LATEST target, the checkpoint being resumed from, the one just
        written, or an in-flight async target. Incomplete dirs older than
        the newest complete checkpoint are crash leftovers — invisible by
        protocol — and are swept too. Caller holds the lock."""
        done = self.complete_checkpoints()
        keepers = {p for _, p in done[-self.keep:]}
        lat = self.latest()
        if lat is not None:
            keepers.add(lat[1])
        for p in (protect, self._resumed_from,
                  self._pending[1] if self._pending else None):
            if p:
                keepers.add(p)
        newest_done = done[-1][0] if done else -1
        for name in os.listdir(self.root):
            n = self._step_of(name)
            if n is None:
                continue
            p = os.path.join(self.root, name)
            if p in keepers:
                continue
            if self._is_complete(p) or n < newest_done:
                shutil.rmtree(p, ignore_errors=True)

    def _choose_dir(self, n):
        """The save target for step ``n`` — `step-<n>` or a fresh
        ``-r<k>`` rewrite generation when the dir already exists. In a
        fleet the choice must be AGREED (two ranks scanning a shared dir
        mid-save would split the checkpoint across generations), so rank
        0 decides and publishes the basename under a sequenced KV key —
        the save counter advances in lockstep on every rank."""
        d = self._dir(n)

        def _occupied(p):
            if not self.multihost:
                return os.path.isdir(p)
            # fleet rule: a dir only counts as a PRIOR save once it wears
            # COMPLETE or the deciding rank's own index — another rank's
            # in-flight partial (it chose this name for the SAME save)
            # must not push the decider onto a fresh generation
            return os.path.exists(os.path.join(p, "COMPLETE")) \
                or os.path.exists(os.path.join(p, "index.p0.json"))

        def _scan():
            out = d
            if _occupied(out):
                k = 1
                while _occupied(f"{out}-r{k}"):
                    k += 1
                out = f"{out}-r{k}"
            return out

        if not self.multihost or self._barrier_fn is not None:
            # single host, or an injected-barrier harness (one process
            # emulating ranks): the local scan is already deterministic
            return _scan()
        from paddle_tpu.distributed import liveness
        from paddle_tpu.distributed.collective import _kv_client
        client = _kv_client()
        key = f"ptpu_ckpt_dir/{self._save_seq}"
        if self._rank == 0:
            d = _scan()
            if os.path.isdir(d):
                # exists but wears neither COMPLETE nor a rank-0 index: a
                # crash leftover, invisible by protocol — wipe it BEFORE
                # publishing the name, or its stale partial indexes
                # (possibly from a LARGER world) would merge into the
                # checkpoint this save is about to publish and overwrite
                # fresh shards with old-trajectory values. Safe exactly
                # because no rank writes before the rendezvous resolves.
                shutil.rmtree(d, ignore_errors=True)
            liveness.set_with_marker(client, key,
                                     os.path.basename(d).encode())
        else:
            raw = liveness.guarded_get_bytes(
                client, key, int(self.barrier_timeout_s * 1e3),
                what=f"checkpoint dir rendezvous {self._save_seq}")
            d = os.path.join(self.root, bytes(raw).decode())
        if self._rank == 0:             # rank 0 owns the KV cleanup
            with self._lock:
                self._kv_garbage.append(("key", key))
        return d

    def save(self, *, data_cursor=None, sync=None):
        """Write a checkpoint of the bound step's CURRENT state. Joins any
        outstanding async write first (propagating its failure). Async
        saves return after the host snapshot — `train.checkpoint_seconds`
        observes exactly that blocking stall. NEVER degrades an existing
        dir: a re-save at an unchanged step writes a fresh ``-r<k>``
        generation beside it; LATEST re-points only once the new one is
        COMPLETE, so a crash mid-rewrite leaves the old checkpoint fully
        durable. In a fleet every rank must call save at the same step
        (the training loop is lockstep by construction)."""
        self.wait()
        n = int(self._step.opt._global_step)
        self._save_seq += 1
        d = self._choose_dir(n)
        part = (self._rank, self._world_size) if self.multihost else None
        use_async = self.use_async if sync is None else not sync
        if self.multihost:
            # fleet saves are SYNCHRONOUS: the publication barrier is a
            # rendezvous every rank must reach at the same save, and this
            # jaxlib's coordination client is not safe for concurrent use
            # from a writer thread racing the step loop's own KV
            # collectives (observed SEGV) — the whole fleet pauses at the
            # boundary together, so there is nothing to overlap anyway
            use_async = False
        t0 = time.perf_counter()
        state = self._state(data_cursor)
        if use_async:
            th = async_save(state, d, on_complete=self._finalize,
                            partition=part)
            self._pending = (th, d)
        else:
            save_sharded(state, d, partition=part)
            self._finalize(d)
        stall = time.perf_counter() - t0
        metrics.histogram("train.checkpoint_seconds").observe(stall)
        flight.record("train.checkpoint", step=n, sync=not use_async,
                      stall_ms=round(stall * 1e3, 3))
        self._last_saved = n
        return d

    def wait(self):
        """Join the outstanding async write, re-raising its error — the
        propagation contract for failed background saves."""
        p, self._pending = self._pending, None
        if p is not None:
            p[0].join()

    def maybe_save(self, data_cursor=None):
        """Periodic trigger: save once ``every`` optimizer steps have
        passed since the last save/restore. No-op when every=0."""
        if self.every <= 0 or self._step is None:
            return None
        n = int(self._step.opt._global_step)
        if n > 0 and n - max(self._last_saved, 0) >= self.every:
            return self.save(data_cursor=data_cursor)
        return None

    # -------------------------------------------------------------- restore
    def restore(self, *, require=False):
        """Load the LATEST complete checkpoint into the bound step: params,
        optimizer state (adopting the CURRENT shardings — this is the
        reshard-on-resume), step clock, lr tensor + scheduler position,
        PRNG chain; then `sync_to_model` so eval/decode/state_dict
        consumers agree with the training state. A checkpoint that fails
        content verification (`CheckpointCorrupt` — bit rot, torn write)
        is SKIPPED and the next-newest complete checkpoint tried: keep-N
        retention exists exactly so one rotten file cannot brick the
        resume. Returns {step, data_cursor, path} or None when nothing is
        there (``require=True`` raises CheckpointIncomplete — the
        rollback path must fail loudly, not restart from init)."""
        self.wait()
        lat = self.latest()
        if lat is None:
            if require:
                raise CheckpointIncomplete(
                    f"no complete checkpoint (LATEST) under {self.root!r} "
                    "to resume from")
            return None
        candidates = [lat] + [c for c in reversed(self.complete_checkpoints())
                              if c[1] != lat[1]]
        first_err = None
        for n, d in candidates:
            try:
                return self._restore_one(n, d)
            except (CheckpointCorrupt, CheckpointIncomplete) as e:
                # bit rot OR a structurally broken dir that still wears a
                # COMPLETE marker (e.g. a prune interrupted mid-rmtree):
                # skip it and try the next-newest — a config mismatch
                # (missing/extra leaves) also walks the list and surfaces
                # as the newest checkpoint's error below
                first_err = first_err if first_err is not None else e
                metrics.counter("train.resume_corrupt_skipped").inc()
                flight.record("train.resume_skipped_corrupt", step=n,
                              error=str(e)[:200])
        raise first_err

    def _restore_one(self, n, d):
        s = self._step
        t0 = time.perf_counter()
        template = {"params": s._params, "opt": s._opt_state}
        loaded = load_sharded(d, template=template)
        # BOTH directions must refuse a mismatched checkpoint: leaves the
        # bound step needs but the checkpoint lacks would silently keep
        # their fresh random init (half-restored model, no error), and
        # extra checkpoint leaves would silently insert into the pytree
        # and make the next step retrace/crash untyped
        from paddle_tpu.distributed.checkpoint import _flatten
        expected = set(_flatten(template))
        got = {k for k in loaded if k.startswith(("params/", "opt/"))}
        missing = sorted(expected - got)
        missing += [k for k in ("meta/global_step", "meta/rng")
                    if k not in loaded]
        if missing:
            raise CheckpointIncomplete(
                f"checkpoint {d!r} lacks {len(missing)} leaves the bound "
                f"step needs (different model/optimizer config?): "
                f"{missing[:5]}{'...' if len(missing) > 5 else ''}")
        extra = sorted(got - expected)
        if extra:
            raise CheckpointCorrupt(
                f"checkpoint {d!r} carries {len(extra)} leaves the bound "
                f"step has no slot for (different model/optimizer?): "
                f"{extra[:5]}{'...' if len(extra) > 5 else ''}")
        for key, val in loaded.items():
            parts = key.split("/")
            arr = getattr(val, "_data", val)
            if parts[0] == "params":
                s._params[parts[1]][parts[2]] = arr
            elif parts[0] == "opt":
                s._opt_state[parts[1]][parts[2]][parts[3]] = arr
        s.opt._global_step = int(loaded["meta/global_step"])
        from paddle_tpu.optimizer.lr import LRScheduler
        if isinstance(s.opt._learning_rate, LRScheduler):
            import json as _json
            if "meta/lr_sched" not in loaded:
                raise CheckpointIncomplete(
                    f"checkpoint {d!r} has no lr-scheduler state but the "
                    "bound optimizer drives one — resuming would restart "
                    "the schedule from epoch 0")
            s.opt._learning_rate.set_state_dict(
                _json.loads(loaded["meta/lr_sched"]))
        s.opt._sync_lr_tensor(s.opt.get_lr())
        s._key = jax.random.wrap_key_data(
            jnp.asarray(loaded["meta/rng"]._data))
        s.consecutive_bad_steps = 0
        s.last_step_ok = True
        s._dirty = True
        s.sync_to_model()
        self._resumed_from = d
        self._last_saved = n
        dt = time.perf_counter() - t0
        metrics.counter("train.resumes").inc()
        flight.record("train.resume", step=n, ms=round(dt * 1e3, 3),
                      path=os.path.basename(d))
        return {"step": n, "path": d,
                "data_cursor": loaded.get("meta/data_cursor")}

    def rollback(self):
        """Bad-step ladder tail: restore the last complete checkpoint
        (counted as `train.rollbacks`); raises CheckpointIncomplete when
        there is none."""
        metrics.counter("train.rollbacks").inc()
        flight.record("train.rollback",
                      at_step=int(self._step.opt._global_step))
        return self.restore(require=True)

    def after_step(self, data_cursor=None):
        """Call once after every `step()`: runs the bad-step ladder, then
        the periodic save. After ``max_consecutive_bad`` non-finite steps
        in a row, rolls back to the last checkpoint and raises
        `TooManyBadSteps` (state is already restored when it raises)."""
        s = self._step
        if 0 < self.max_consecutive_bad <= s.consecutive_bad_steps:
            bad = s.consecutive_bad_steps
            try:
                info = self.rollback()
            except CheckpointIncomplete as e:
                raise TooManyBadSteps(
                    f"{bad} consecutive non-finite steps and no checkpoint "
                    f"to roll back to: {e}") from e
            raise TooManyBadSteps(
                f"{bad} consecutive non-finite steps — rolled back to "
                f"step {info['step']} ({info['path']})")
        if s.last_step_ok:
            self.maybe_save(data_cursor=data_cursor)

    # ------------------------------------------------------------- SIGTERM
    def install_sigterm(self):
        """SIGTERM -> finish the current step, synchronous final
        checkpoint, clean exit (the training mirror of serve's
        `install_sigterm_drain`). The handler only sets a flag — the LOOP
        observes `should_stop` at the next step boundary, so the signal
        can never corrupt a half-applied update. Returns the handler."""
        def _handler(signum, frame):   # noqa: ARG001 — signal signature
            self._stop.set()
            flight.record("train.sigterm")
        signal.signal(signal.SIGTERM, _handler)
        return _handler

    @property
    def should_stop(self):
        return self._stop.is_set()

    def request_stop(self):
        """Programmatic preemption (tests, embedding loops)."""
        self._stop.set()

    # -------------------------------------------------------- managed loop
    def run(self, batch_fn, *, until_step, resume=True, data_cursor=0,
            max_batches=None, on_step=None, install_sigterm=False):
        """Preemption-safe training loop around the bound step.

        ``batch_fn(cursor)`` -> (x, y) or (x, y, loss_mask) for data
        cursor ``cursor`` — the cursor advances on EVERY consumed batch
        (bad steps included: a batch that produced NaNs is not retried),
        while the optimizer clock advances only on applied steps. Resumes
        from LATEST first (unless ``resume=False``; then ``data_cursor``
        seeds the cursor), stops cleanly at ``until_step`` or on SIGTERM,
        and always leaves a final synchronous checkpoint behind.
        ``max_batches`` bounds TOTAL batches consumed this invocation —
        the termination backstop when rollback is disabled
        (``max_consecutive_bad=0``) and persistent NaNs keep the step
        clock from ever reaching ``until_step``. Returns the list of
        per-step losses from THIS invocation. TooManyBadSteps propagates
        (state already rolled back)."""
        if install_sigterm:
            self.install_sigterm()
        cursor = int(data_cursor)
        if resume:
            info = self.restore()
            if info is not None and info.get("data_cursor") is not None:
                cur = info["data_cursor"]
                if isinstance(cur, (list, tuple)):
                    # Model.fit writes [epoch, batch] — run() cannot map
                    # it onto batch_fn's flat index space; the reverse
                    # direction refuses symmetrically in fit
                    raise ValueError(
                        f"checkpoint at {info['path']} has data_cursor="
                        f"{cur!r}; CheckpointManager.run needs the flat "
                        "integer cursor it writes — resume fit-written "
                        "checkpoints with Model.fit(checkpoint_manager=)")
                cursor = int(cur)
        s = self._step
        losses, consumed = [], 0
        while s.opt._global_step < until_step and not self.should_stop:
            if max_batches is not None and consumed >= max_batches:
                flight.record("train.run_batch_budget", consumed=consumed)
                break
            batch = batch_fn(cursor)
            cursor += 1
            consumed += 1
            loss = s.step(*batch)
            losses.append(loss)
            if on_step is not None:
                on_step(int(s.opt._global_step), loss, s.last_step_ok)
            self.after_step(data_cursor=cursor)
        self.finalize(data_cursor=cursor)
        return losses

    def _saved_cursor(self, path):
        """The data cursor recorded in a checkpoint's index (literal-only
        read, no shard IO), or None when unreadable/absent."""
        from paddle_tpu.distributed.checkpoint import read_literal
        return read_literal(path, "meta/data_cursor")

    def finalize(self, data_cursor=None):
        """Drain + final synchronous checkpoint. Skipped only when LATEST
        already captures BOTH the current optimizer step and the current
        data cursor — bad steps advance the cursor without advancing the
        step clock, and losing that advance would re-feed the same
        NaN-producing batches on every resume."""
        self.wait()
        lat = self.latest()
        if lat is None or lat[0] != int(self._step.opt._global_step) or (
                data_cursor is not None
                and self._saved_cursor(lat[1]) != data_cursor):
            self.save(data_cursor=data_cursor, sync=True)
