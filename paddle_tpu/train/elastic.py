"""Elastic multi-host training: bounded-time termination + restart.

The training mirror of the serving plane's contract (docs/ROBUSTNESS.md):
every distributed training step terminates in bounded time with progress,
a checkpoint, or a TYPED error — never an indefinite collective hang.
Three pieces:

- :class:`FleetReducer` — cross-process data parallelism for runtimes
  that cannot compile one program over all processes (0.4.x CPU jaxlib):
  each rank computes grads over ITS shard of the global batch in its own
  donated program (`ScanTrainStep(grad_reducer=...)` split mode), and the
  reducer averages loss+grads through the coordination-service KV
  allgather (`distributed/collective.py`), liveness-guarded so a dead
  peer resolves as typed :class:`PeerLost` within the heartbeat deadline.
  A fleet STOP VOTE rides the same payload: any rank's SIGTERM flag is
  max-reduced every step, so the whole fleet agrees to stop at the SAME
  step boundary and drains into one coordinated final checkpoint — the
  multi-host `install_sigterm` contract.
- :func:`run_elastic_worker` — the per-rank training loop: per-step
  heartbeats (`distributed/liveness.py`), a ``trainer``-role lease in the
  elastic registry (`fleet/elastic.py` — the same registry serving rides),
  multi-host `CheckpointManager` saves (barrier-published, "complete or
  invisible" fleet-wide), and the `train.peer_dead` chaos site (the armed
  rank SIGKILLs itself at a step boundary — the deterministic stand-in
  for spot reclaim).
- :class:`ElasticController` — the supervising relauncher: spawns the
  fleet, classifies exits (rc 0 = done; ``EXIT_PEER_LOST`` = a healthy
  survivor that detected a dead peer and aborted typed; anything else =
  the dead peer itself), reforms at the largest allowed world size the
  survivors support, and relaunches — the new fleet resumes from the
  last fleet-complete checkpoint, resharding ZeRO-1 state to the new dp
  plan (PR 9's reshard-on-resume), and recompiles exactly once
  (test_no_retrace pin).

Determinism note: the reducer's mean runs in f32 over the rank-ordered
[P, N] stack, so two dp=k runs from the same checkpoint produce
bit-identical trajectories — the elastic drill's float-ulp parity pin
(tests/test_train_elastic.py).
"""
from __future__ import annotations

import os
import signal
import sys
import time

import numpy as np

from paddle_tpu.distributed.liveness import LivenessMonitor, PeerLost
from paddle_tpu.distributed import liveness
from paddle_tpu.observability import metrics
from paddle_tpu.observability.flight_recorder import flight
from paddle_tpu.testing import faults

__all__ = ["FleetReducer", "run_elastic_worker", "elastic_worker_main",
           "ElasticController", "EXIT_PEER_LOST", "PeerLost",
           "spawn_local_fleet"]

# the exit code a SURVIVOR uses after detecting a dead peer: the process
# is healthy (it can be relaunched into the reformed fleet) — the
# controller distinguishes it from the dead peer's own exit (signal /
# traceback rc). 23 collides with no shell/timeout/signal convention.
EXIT_PEER_LOST = 23


class FleetReducer:
    """Average (loss, grads) across the training fleet + the stop vote.

    Packs every grad leaf, the loss, and this rank's stop flag into ONE
    f32 vector per step — one KV allgather, not one per leaf — then
    unpacks the rank-mean. ``fleet_stop`` reads True once ANY rank voted
    stop at this step boundary; every rank sees the identical vote, so
    the fleet stops (and checkpoints) together. All reads are
    liveness-guarded: a peer that dies mid-step surfaces as typed
    PeerLost on every survivor within the monitor deadline.
    """

    def __init__(self, monitor=None):
        self.monitor = monitor
        self.request_stop = False      # this rank's vote (set by SIGTERM)
        self.fleet_stop = False        # the fleet's agreed answer
        self.reduces = 0

    def __call__(self, loss, grads):
        import jax
        from paddle_tpu.distributed import collective
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        flat = np.concatenate(
            [np.asarray(a, np.float32).ravel() for a in leaves]
            + [np.asarray(loss, np.float32).ravel(),
               np.asarray([1.0 if self.request_stop else 0.0], np.float32)])
        if jax.process_count() > 1:
            stacked = np.asarray(collective._proc_allgather(flat))
        else:
            stacked = flat[None]       # degenerate 1-rank fleet
        self.reduces += 1
        self.fleet_stop = bool(stacked[:, -1].max() > 0.0)
        # f32 mean over the rank-ordered stack: deterministic for a fixed
        # world size — the resume-parity contract depends on this
        mean = stacked[:, :-1].mean(axis=0, dtype=np.float32)
        out, pos = [], 0
        for a in leaves:
            n = int(np.size(a))        # scalars pack as 1, EMPTY leaves
            #                            as 0 — `prod(shape) or 1` would
            #                            shift every later leaf by one
            out.append(mean[pos:pos + n].reshape(np.shape(a)))
            pos += n
        return mean[pos], jax.tree_util.tree_unflatten(treedef, out)


def _escalate_if_peer_dead(exc, monitor, *, wait_s=None):
    """A collective that failed with a NON-timeout transport error (a
    dead coordinator drops connections rather than timing out) is still
    usually a dead peer: give the heartbeat deadline a moment to confirm
    and convert to typed PeerLost; otherwise re-raise the original."""
    if monitor is None or isinstance(exc, PeerLost):
        raise exc
    deadline = time.time() + (wait_s if wait_s is not None
                              else monitor.deadline_s + 2.0)
    while time.time() < deadline:
        monitor.rebeat()
        monitor.check(context=f"after {type(exc).__name__}")
        time.sleep(0.25)
    raise exc


def run_elastic_worker(make_step, batch_fn, *, root, until_step, every=2,
                       keep=3, deadline_s=15.0, hb_dir=None,
                       registry_dir=None, on_step=None,
                       install_sigterm=True, barrier_timeout_s=60.0,
                       max_batches=None):
    """One rank of an elastic training fleet (docs/ROBUSTNESS.md
    "Multi-host training").

    make_step : ``make_step(grad_reducer) -> ScanTrainStep`` — the
                builder receives the fleet reducer (None on a world-1
                fleet) so the step compiles in split grads/apply mode.
    batch_fn  : ``batch_fn(cursor, rank, world) -> (x, y)`` — this
                rank's SHARD of global batch ``cursor``. The cursor is
                the global data clock; sharding by (rank, world) is the
                caller's contract so a resumed smaller fleet re-shards
                the same global stream.
    root      : shared checkpoint root (heartbeats live under
                ``<root>/hb`` unless ``hb_dir`` overrides; reusing the
                dir across relaunch attempts is safe — the monitor
                ignores heartbeats/tombstones from before its own birth
                — but per-attempt dirs keep post-mortems legible, see
                `spawn_local_fleet`).
    deadline_s: size it ABOVE the fleet's worst-case per-step SKEW —
                guarded waiters re-beat while waiting and shard writes
                re-beat per file, but a rank inside a long jit compile
                cannot beat, so the first post-reform compile's spread
                across ranks bounds the deadline from below.

    Returns {rank, world, resumed_step, losses, stopped}. Raises typed
    :class:`PeerLost` when a peer dies — the caller should exit
    ``EXIT_PEER_LOST`` (see :func:`elastic_worker_main`) so the
    controller can count it as a relaunchable survivor.
    """
    from paddle_tpu.distributed.parallel import (get_rank, get_world_size,
                                                 init_parallel_env)
    init_parallel_env()
    rank, world = get_rank(), get_world_size()
    monitor = None
    if world > 1:
        monitor = LivenessMonitor(hb_dir or os.path.join(str(root), "hb"),
                                  rank, world, deadline_s=deadline_s)
        liveness.install(monitor)
        monitor.beat(-1)               # visible before the first compile
    reducer = FleetReducer(monitor) if world > 1 else None
    step = make_step(reducer)
    from paddle_tpu.train.fault_tolerance import CheckpointManager
    mgr = CheckpointManager(root, step, every=every, keep=keep,
                            world=(rank, world),
                            barrier_timeout_s=barrier_timeout_s)
    if install_sigterm:
        mgr.install_sigterm()
    lease = None
    if registry_dir:
        from paddle_tpu.distributed.fleet.elastic import (NodeRegistry,
                                                          role_node_id)
        lease = NodeRegistry(registry_dir,
                             node_id=role_node_id("trainer", str(rank)),
                             endpoint=f"rank-{rank}", ttl=4 * deadline_s)
        lease.register()
    flight.record("train.elastic_worker", rank=rank, world=world,
                  until=int(until_step))
    try:
        cursor = 0
        info = mgr.restore()
        resumed = 0
        if info is not None:
            resumed = info["step"]
            if info.get("data_cursor") is not None:
                cursor = int(info["data_cursor"])
        losses, consumed, stopped = [], 0, False
        while int(step.opt._global_step) < int(until_step):
            if max_batches is not None and consumed >= max_batches:
                break
            if faults.ENABLED and faults.fire("train.peer_dead") \
                    and faults.remaining("train.peer_dead") == 0:
                # spot reclaim, deterministically: the LAST armed charge
                # (``times=k`` = die at the k-th step boundary) SIGKILLs
                # this rank WITHOUT cleanup — peers must detect via
                # heartbeats, exactly like a real preemption
                os.kill(os.getpid(), signal.SIGKILL)
            if monitor is not None:
                monitor.beat(int(step.opt._global_step))
            if reducer is not None:
                reducer.request_stop = mgr.should_stop
            try:
                loss = step.step(*batch_fn(cursor, rank, world))
            except PeerLost:
                raise
            except Exception as e:  # noqa: BLE001 — classify (dead peer?)
                _escalate_if_peer_dead(e, monitor)
            cursor += 1
            consumed += 1
            losses.append(loss)
            if on_step is not None:
                on_step(int(step.opt._global_step), loss, step.last_step_ok)
            mgr.after_step(data_cursor=cursor)
            if (reducer.fleet_stop if reducer is not None
                    else mgr.should_stop):
                # the stop vote resolved true on EVERY rank at this same
                # boundary: drain together into one final checkpoint
                stopped = True
                break
        mgr.finalize(data_cursor=cursor)
        return {"rank": rank, "world": world, "resumed_step": resumed,
                "losses": losses, "stopped": stopped}
    except PeerLost:
        if monitor is not None and rank == 0:
            # rank 0 hosts the coordination service: its exit hard-kills
            # every process still attached (jaxlib fatally terminates on
            # a dropped service connection), so the leader LINGERS until
            # the other survivors have either gone silent or published
            # their own typed tombstone — staggered detection must not
            # turn typed survivor exits into SIGABRTs
            monitor.wait_for_cascade()
        raise
    finally:
        if lease is not None:
            try:
                lease.leave()
            except OSError:
                pass
        if monitor is not None:
            liveness.uninstall()


def _hard_exit_peer_lost(e):
    """Print the one-line typed error (the flight ring was already
    dumped by the monitor) and HARD-EXIT ``EXIT_PEER_LOST``: with a dead
    peer in the fleet, jaxlib's distributed-client teardown can block
    for ~90 s and then SIGABRT (rc -6), which the controller would
    misread as a dead peer instead of a relaunchable survivor — the
    typed rc IS the contract, so skip interpreter teardown entirely
    (bench.py's os._exit lesson)."""
    print(f"PeerLost: {e}", flush=True)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(EXIT_PEER_LOST)


def elastic_worker_main(make_step, batch_fn, **kw) -> int:
    """CLI-shaped wrapper: run one rank; returns 0 on a clean finish.
    On a typed PeerLost it never returns — see
    :func:`_hard_exit_peer_lost`. Anything else propagates."""
    try:
        run_elastic_worker(make_step, batch_fn, **kw)
    except PeerLost as e:
        _hard_exit_peer_lost(e)
    return 0


class ElasticController:
    """Supervising relauncher: reform the fleet at the surviving world
    size and resume from the last fleet-complete checkpoint.

    spawn         : ``spawn(world_size, attempt) -> [proc, ...]`` — proc
                    needs ``poll() -> rc|None``, ``kill()``, ``wait()``
                    (subprocess.Popen qualifies). The spawner owns env
                    wiring (fresh coordinator port per attempt!) and the
                    per-rank command line.
    world_size    : the initial fleet size.
    allowed_sizes : world sizes the training math supports (e.g. divisors
                    of the global batch). Default: every size from
                    world_size down to 1. After a failure the controller
                    picks the LARGEST allowed size <= the survivor count.
    min_world     : below this, give up instead of limping.
    max_restarts  : relaunch budget.
    settle_s      : after the first failed exit, how long the rest get to
                    exit typed on their own before being killed (size it
                    above the workers' liveness deadline).
    registry_dir  : optional — observe the trainer-role leases for the
                    flight record at each decision point.

    ``run()`` returns the final fleet's exit code: 0 when an attempt
    finishes clean, 1 when restarts/min_world are exhausted.
    """

    def __init__(self, spawn, *, world_size, allowed_sizes=None,
                 min_world=1, max_restarts=2, settle_s=60.0,
                 registry_dir=None, poll_s=0.2):
        self.spawn = spawn
        self.world_size = int(world_size)
        self.allowed = sorted(set(allowed_sizes)
                              if allowed_sizes is not None
                              else range(1, self.world_size + 1))
        self.min_world = int(min_world)
        self.max_restarts = int(max_restarts)
        self.settle_s = float(settle_s)
        self.registry_dir = registry_dir
        self.poll_s = float(poll_s)
        self.attempts = []             # [(world, [rc, ...])] per attempt

    def _registry_view(self):
        if not self.registry_dir:
            return None
        try:
            from paddle_tpu.distributed.fleet.elastic import NodeRegistry
            return sorted(NodeRegistry(self.registry_dir).alive_nodes())
        except OSError:
            return None

    def _await(self, procs):
        """Collect every proc's rc. After the FIRST non-zero exit the
        rest get ``settle_s`` to finish their typed abort, then are
        killed — a survivor that NEVER detects the death would otherwise
        hang the controller exactly like the collective it replaced."""
        first_bad = None
        while True:
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                return rcs
            if first_bad is None:
                if any(rc not in (None, 0) for rc in rcs):
                    first_bad = time.time()
            elif time.time() - first_bad > self.settle_s:
                for p, rc in zip(procs, rcs):
                    if rc is None:
                        try:
                            p.kill()
                        except OSError:
                            pass
                return [p.wait() for p in procs]
            time.sleep(self.poll_s)

    def decide_next_world(self, rcs):
        """Pure decision: the largest allowed world size the survivors
        (typed PeerLost exits — healthy, relaunchable) can field, or 0
        when none is acceptable."""
        survivors = sum(1 for rc in rcs if rc == EXIT_PEER_LOST)
        fit = [w for w in self.allowed if w <= survivors]
        nxt = max(fit) if fit else 0
        return nxt if nxt >= self.min_world else 0

    def run(self):
        world = self.world_size
        for attempt in range(self.max_restarts + 1):
            flight.record("train.elastic_launch", attempt=attempt,
                          world=world, registry=self._registry_view())
            procs = self.spawn(world, attempt)
            rcs = self._await(procs)
            self.attempts.append((world, rcs))
            if all(rc == 0 for rc in rcs):
                return 0
            nxt = self.decide_next_world(rcs)
            flight.record("train.elastic_failure", attempt=attempt,
                          world=world, rcs=[int(r) for r in rcs],
                          next_world=nxt)
            if nxt == 0 or attempt >= self.max_restarts:
                return 1
            metrics.counter("train.elastic_restarts").inc()
            world = nxt
        return 1


# --------------------------------------------------------------- drill CLI
#
# `python -m paddle_tpu.train.elastic --rank R --world W --root DIR ...`
# runs ONE rank of a self-contained tiny-GPT elastic worker — the drill
# entry the chaos tests, bench_train_elastic, and the docs/ROBUSTNESS.md
# ops drills all share. `spawn_local_fleet` is the matching controller-
# side spawner (fresh coordinator port per attempt, per-rank logs/env).


def _drill_batch_fn(batch, seq, vocab):
    """Deterministic GLOBAL batch stream, sharded by contiguous rows —
    the same global batch at any world size, so a reformed fleet
    re-shards the identical data stream."""
    def fn(cursor, rank, world):
        rng = np.random.RandomState(1000 + int(cursor))
        ids = rng.randint(0, vocab, (batch, seq + 1))
        shard = batch // world
        lo, hi = rank * shard, (rank + 1) * shard
        return (ids[lo:hi, :-1].astype(np.int32),
                ids[lo:hi, 1:].astype(np.int32))
    return fn


def _drill_main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        "paddle_tpu.train.elastic",
        description="one rank of the elastic multi-host training drill "
                    "(tiny GPT; see docs/ROBUSTNESS.md 'Multi-host "
                    "training')")
    ap.add_argument("--root", required=True)
    ap.add_argument("--until-step", type=int, required=True)
    ap.add_argument("--every", type=int, default=2)
    ap.add_argument("--deadline-s", type=float, default=10.0)
    ap.add_argument("--registry-dir", default=None)
    ap.add_argument("--hb-dir", default=None,
                    help="heartbeat/tombstone dir — MUST be per-attempt "
                         "(a relaunched fleet must not read the previous "
                         "attempt's stale heartbeats or tombstones)")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=16)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--seed", type=int, default=5)
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.train.scan_step import ScanTrainStep

    def make_step(reducer):
        paddle.seed(args.seed)
        cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                        num_layers=args.layers, num_heads=2,
                        intermediate_size=2 * args.hidden,
                        max_position_embeddings=args.seq,
                        hidden_dropout=0.0, attention_dropout=0.0)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        return ScanTrainStep(model, opt, microbatches=1,
                             grad_reducer=reducer)

    step_box = {}

    def make_and_box(reducer):
        step_box["step"] = make_step(reducer)
        return step_box["step"]

    try:
        out = run_elastic_worker(
            make_and_box, _drill_batch_fn(args.batch, args.seq, args.vocab),
            root=args.root, until_step=args.until_step, every=args.every,
            deadline_s=args.deadline_s, registry_dir=args.registry_dir,
            hb_dir=args.hb_dir,
            on_step=lambda n, loss, ok: print(f"STEP {n} {loss!r} t="
                                              f"{time.time():.3f}",
                                              flush=True))
    except PeerLost as e:
        _hard_exit_peer_lost(e)
    print(f"RESUMED {out['resumed_step']}", flush=True)
    s = step_box["step"]
    print(f"DONE {int(s.opt._global_step)} compiles={s.compile_count} "
          f"stopped={out['stopped']}", flush=True)
    return 0


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_local_fleet(world, *, root, until_step, log_dir, every=2,
                      deadline_s=10.0, registry_dir=None, batch=4,
                      env_for_rank=None, attempt=0, extra_args=()):
    """Spawn ``world`` local drill ranks (the controller-side half of the
    CLI above): fresh coordinator port per call, per-rank
    ``rank<r>.a<attempt>.log`` files under ``log_dir``, launch-style env
    (``PADDLE_TRAINER_ID``/``PADDLE_TRAINERS_NUM``/``PADDLE_MASTER``).
    ``env_for_rank(rank) -> dict`` merges per-rank extras (e.g. arming
    ``PADDLE_FAULTS=train.peer_dead`` on the victim). Returns
    [subprocess.Popen, ...] — feed to :class:`ElasticController` via a
    closure over this function."""
    import subprocess
    os.makedirs(log_dir, exist_ok=True)
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    procs = []
    for rank in range(int(world)):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "",           # 1 CPU device: fastest child compile
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
        })
        env.pop("PADDLE_FAULTS", None)
        if env_for_rank is not None:
            env.update(env_for_rank(rank) or {})
        cmd = [sys.executable, "-m", "paddle_tpu.train.elastic",
               "--root", str(root), "--until-step", str(until_step),
               "--every", str(every), "--deadline-s", str(deadline_s),
               "--batch", str(batch),
               # per-ATTEMPT heartbeat dir: stale heartbeats/tombstones
               # from a previous attempt must not poison the new fleet
               "--hb-dir", os.path.join(str(root), f"hb-a{int(attempt)}"),
               *map(str, extra_args)]
        if registry_dir:
            cmd += ["--registry-dir", str(registry_dir)]
        log = open(os.path.join(log_dir, f"rank{rank}.a{attempt}.log"),
                   "ab")
        p = subprocess.Popen(cmd, env=env, stdout=log,
                             stderr=subprocess.STDOUT)
        p._ptpu_log = log              # closed by the caller's GC; handle
        #                                kept so the file outlives Popen
        procs.append(p)
    return procs


if __name__ == "__main__":
    raise SystemExit(_drill_main())
