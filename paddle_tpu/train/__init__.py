"""Training-step capture + fault tolerance.

scan_step.py — stacked [nl, ...] params, lax.scan forward/backward,
gradient-accumulation microbatching, ZeRO-1 sharded optimizer update,
buffer donation, in-program bad-step skip. Engine
(distributed/auto_parallel.py) and hapi Model route here when the
(model, optimizer) pair supports it.

fault_tolerance.py — preemption-safe checkpointing around the step:
durable checksummed checkpoints with a crash-consistent LATEST pointer,
SIGTERM -> drain -> checkpoint -> exit, kill -9 resume with bit-identical
loss trajectory, and the consecutive-bad-step rollback ladder.
"""
from paddle_tpu.train.scan_step import ScanTrainStep, ScanUnsupported
from paddle_tpu.train.fault_tolerance import (CheckpointCorrupt,
                                              CheckpointIncomplete,
                                              CheckpointManager,
                                              TooManyBadSteps)

__all__ = ["ScanTrainStep", "ScanUnsupported", "CheckpointManager",
           "TooManyBadSteps", "CheckpointCorrupt", "CheckpointIncomplete"]
