"""Training-step capture: the scan-over-layers donated GPT hot path.

See scan_step.py — stacked [nl, ...] params, lax.scan forward/backward,
gradient-accumulation microbatching, ZeRO-1 sharded optimizer update,
buffer donation. Engine (distributed/auto_parallel.py) and hapi Model
route here when the (model, optimizer) pair supports it.
"""
from paddle_tpu.train.scan_step import ScanTrainStep, ScanUnsupported

__all__ = ["ScanTrainStep", "ScanUnsupported"]
