"""Training-step capture + fault tolerance.

scan_step.py — stacked [nl, ...] params, lax.scan forward/backward,
gradient-accumulation microbatching, ZeRO-1 sharded optimizer update,
buffer donation, in-program bad-step skip. Engine
(distributed/auto_parallel.py) and hapi Model route here when the
(model, optimizer) pair supports it.

fault_tolerance.py — preemption-safe checkpointing around the step:
durable checksummed checkpoints with a crash-consistent LATEST pointer,
SIGTERM -> drain -> checkpoint -> exit, kill -9 resume with bit-identical
loss trajectory, and the consecutive-bad-step rollback ladder. On a
multi-host fleet: per-rank key-partitioned shard writes published behind
a coordination-KV barrier (complete-or-invisible fleet-wide).

elastic.py — elastic multi-host training: the FleetReducer (cross-process
grad averaging + the SIGTERM stop vote), per-step liveness heartbeats
converting dead-peer collective hangs into typed PeerLost on every
survivor, and the ElasticController that relaunches the fleet at the
surviving world size from the last fleet-complete checkpoint.
"""
from paddle_tpu.train.scan_step import ScanTrainStep, ScanUnsupported
from paddle_tpu.train.fault_tolerance import (CheckpointCorrupt,
                                              CheckpointIncomplete,
                                              CheckpointManager,
                                              TooManyBadSteps)
from paddle_tpu.train.elastic import (EXIT_PEER_LOST, ElasticController,
                                      FleetReducer, PeerLost,
                                      elastic_worker_main,
                                      run_elastic_worker)

__all__ = ["ScanTrainStep", "ScanUnsupported", "CheckpointManager",
           "TooManyBadSteps", "CheckpointCorrupt", "CheckpointIncomplete",
           "PeerLost", "FleetReducer", "ElasticController",
           "run_elastic_worker", "elastic_worker_main", "EXIT_PEER_LOST"]
