"""Fleet observability plane: cross-process traces + aggregated metrics.

Every paddle_tpu process keeps its OWN registry (`observability/__init__`)
and answers the serve wire's STATS / PROMETHEUS / TRACE_EXPORT /
DEBUG_DUMP ops; this module is the pull side that turns those per-process
views into fleet-level ones (docs/OBSERVABILITY.md "Fleet tracing" and
"Fleet metrics plane"):

- :class:`TraceCollector` pulls each member's span buffer for ONE trace id
  (TRACE_EXPORT, op 11) and stitches the exports into a single Chrome
  trace: one ``pid`` lane per process, named ``role:node_id`` via
  ``process_name`` metadata, timestamps already wall-rebased by the
  exporting registry so the lanes line up without clock negotiation
  (microsecond-level NTP skew shifts lanes, never reorders a process's
  own spans).
- :class:`FleetMetrics` ingests per-member STATS snapshots — fed by the
  router's existing poll loop (`Router.attach_fleet`) or this module's
  own scrape loop — and exposes: an exact counter-sum rollup, merged
  histograms (counts/totals exact, quantiles count-weighted estimates),
  per-replica operational gauges (pages in use, degradation level), one
  re-labeled ``{role,replica}`` Prometheus exposition, and a JSON
  snapshot API (`snapshot_for`) shaped exactly like a direct STATS pull
  so the autoscaler's ``stats_fn`` can ride the shared scrape instead of
  opening its own per-replica connections.
- :func:`start_fleet_exporter` serves both over stdlib HTTP
  (``GET /metrics`` and ``GET /fleet``); ``python -m
  paddle_tpu.observability.fleet`` is the standalone CLI for fleets
  without a router in the loop.

Stdlib-only, like everything under ``observability/``.
"""
from __future__ import annotations

import argparse
import json
import threading
import time

__all__ = ["TraceCollector", "FleetMetrics", "start_fleet_exporter",
           "scrape_once", "main"]


def _wire_client(endpoint: str, secret=None, timeout=5.0):
    """One probe-grade authed wire client for ``endpoint`` (host:port).
    Import is lazy so the metrics plane never drags serve (and numpy/jax)
    into processes that only merge snapshots."""
    from paddle_tpu.inference.serve import RemotePredictor
    host, port = str(endpoint).rsplit(":", 1)
    return RemotePredictor(host, int(port), timeout=timeout,
                           secret=secret, connect_retries=1,
                           retry_deadline_s=min(timeout, 3.0))


# ---------------------------------------------------------------- tracing


class TraceCollector:
    """Pull + stitch one request's spans from every fleet member.

    >>> col = TraceCollector({"r0": "127.0.0.1:7001",
    ...                       "router:a": "127.0.0.1:7000"},
    ...                      secret="fleet")
    >>> trace = col.collect(trace_id)     # ONE Chrome trace, all processes
    >>> json.dump(trace, open("trace.json", "w"))    # -> Perfetto
    """

    def __init__(self, members: dict, secret=None, timeout=5.0):
        self._members = dict(members)      # member id -> "host:port"
        self._secret = secret
        self._timeout = float(timeout)

    def pull(self, trace_id: str) -> list[dict]:
        """Every member's raw TRACE_EXPORT body for ``trace_id`` (hex).
        A dead or trace-less member contributes nothing — partial fleets
        still stitch (the trace just misses that process's lane)."""
        exports = []
        for mid, ep in sorted(self._members.items()):
            cli = None
            try:
                cli = _wire_client(ep, self._secret, self._timeout)
                body = cli.trace_export(trace_id)
            except (OSError, ConnectionError, ValueError, RuntimeError):
                continue
            finally:
                if cli is not None:
                    try:
                        cli.close()
                    except OSError:
                        pass
            if body.get("spans"):
                body.setdefault("member_id", mid)
                exports.append(body)
        return exports

    @staticmethod
    def stitch(exports: list[dict]) -> dict:
        """Merge TRACE_EXPORT bodies into ONE Chrome trace. Each export
        becomes one ``pid`` lane labeled ``role:node_id``; span timestamps
        are already unix-epoch microseconds, rebased here to the earliest
        span so the trace starts at t=0."""
        events = []
        t0 = min((ev["ts"] for ex in exports for ev in ex["spans"]),
                 default=0.0)
        for lane, ex in enumerate(sorted(
                exports, key=lambda e: (e.get("node") or {}).get(
                    "node_id") or "")):
            node = ex.get("node") or {}
            label = f"{node.get('role') or 'process'}:" \
                    f"{node.get('node_id') or node.get('pid') or lane}"
            events.append({"name": "process_name", "ph": "M", "pid": lane,
                           "tid": 0, "args": {"name": label}})
            for ev in ex["spans"]:
                ev = dict(ev)
                ev["pid"] = lane
                ev["ts"] = round(ev["ts"] - t0, 3)
                events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def collect(self, trace_id: str) -> dict:
        """`pull` + `stitch`: the one-call path."""
        return self.stitch(self.pull(trace_id))


# ---------------------------------------------------------------- metrics

# count-weighted mergeable summary fields; quantiles are estimated
# separately (a reservoir's exact quantiles do not merge)
_HIST_EXACT = ("count", "total")


def merge_histograms(summaries: list[dict]) -> dict:
    """Merge per-process histogram summaries: ``count``/``total`` are
    exact sums, ``min``/``max`` exact extrema, ``mean`` derived, and
    ``p50``/``p99`` count-weighted estimates (the per-process reservoirs
    cannot be merged exactly; the estimate is exact when one process
    dominates and bounded by the per-process values always)."""
    out = {"count": 0, "total": 0.0, "min": None, "max": None,
           "mean": None, "p50": None, "p99": None}
    wsum = {"p50": 0.0, "p99": 0.0}
    wcnt = {"p50": 0, "p99": 0}
    for s in summaries:
        c = int(s.get("count") or 0)
        out["count"] += c
        out["total"] += float(s.get("total") or 0.0)
        for k, pick in (("min", min), ("max", max)):
            v = s.get(k)
            if v is not None:
                out[k] = v if out[k] is None else pick(out[k], v)
        for q in ("p50", "p99"):
            v = s.get(q)
            if v is not None and c:
                wsum[q] += float(v) * c
                wcnt[q] += c
    if out["count"]:
        out["mean"] = out["total"] / out["count"]
    for q in ("p50", "p99"):
        if wcnt[q]:
            out[q] = wsum[q] / wcnt[q]
    return out


class FleetMetrics:
    """Rolling fleet view of per-member STATS snapshots.

    ``ingest`` is called by whoever scrapes (the router's poll loop via
    `Router.attach_fleet`, the standalone CLI, or a test directly);
    everything else is a read. Members age out after ``ttl_s`` without a
    fresh snapshot so a departed replica's counters stop inflating the
    rollup (its contribution is a VIEW, not a merged total — fleet
    counters are sums over currently-live members by design; a restart
    resets a member's counters exactly like a process restart resets its
    own registry)."""

    def __init__(self, ttl_s: float = 60.0):
        self._lock = threading.Lock()
        self._ttl = float(ttl_s)
        # member id -> {"role","endpoint","snapshot","t"}
        self._members: dict[str, dict] = {}

    # ------------------------------------------------------------- feeding

    def ingest(self, member_id: str, role: str | None, endpoint: str,
               snapshot: dict):
        """Fold one member's STATS snapshot in. ``snapshot`` is the STATS
        JSON body (``counters``/``gauges``/``histograms`` + extras); the
        member's self-declared role inside it wins over ``role``."""
        if not isinstance(snapshot, dict):
            raise TypeError("snapshot must be the STATS dict")
        srole = snapshot.get("role") or role or "replica"
        with self._lock:
            self._members[str(member_id)] = {
                "role": str(srole), "endpoint": str(endpoint),
                "snapshot": snapshot, "t": time.monotonic()}

    def drop(self, member_id: str):
        with self._lock:
            self._members.pop(str(member_id), None)

    def _live(self) -> dict[str, dict]:
        now = time.monotonic()
        with self._lock:
            for mid in [m for m, e in self._members.items()
                        if now - e["t"] > self._ttl]:
                del self._members[mid]
            return {mid: dict(e) for mid, e in self._members.items()}

    # ------------------------------------------------------------- reading

    def members(self) -> dict[str, dict]:
        """Live member id -> {role, endpoint, age_s}."""
        now = time.monotonic()
        return {mid: {"role": e["role"], "endpoint": e["endpoint"],
                      "age_s": round(now - e["t"], 3)}
                for mid, e in self._live().items()}

    def snapshot_for(self, endpoint: str) -> dict | None:
        """The latest ingested snapshot for the member at ``endpoint`` —
        the autoscaler's ``stats_fn(endpoint)`` contract (same JSON a
        direct STATS pull returns, None when the plane has no fresh view),
        so scaling decisions ride the shared scrape loop instead of a
        second per-replica pull fan-out."""
        for e in self._live().values():
            if e["endpoint"] == str(endpoint):
                return e["snapshot"]
        return None

    @property
    def stats_fn(self):
        """Bound `snapshot_for` — pass as ``AutoScaler(stats_fn=...)``."""
        return self.snapshot_for

    def rollup(self) -> dict:
        """Fleet-level aggregation over live members: exact counter sums,
        additive gauge sums, merged histograms, plus the operational
        ``fleet`` digest (aggregate tok/s, fleet TTFT/TPOT, per-replica
        pages-in-use and degradation level)."""
        live = self._live()
        counters: dict[str, float] = {}
        gauges: dict[str, float] = {}
        hists: dict[str, list] = {}
        per = {}
        for mid, e in sorted(live.items()):
            snap = e["snapshot"]
            for name, v in (snap.get("counters") or {}).items():
                counters[name] = counters.get(name, 0) + v
            for name, v in (snap.get("gauges") or {}).items():
                gauges[name] = gauges.get(name, 0) + v
            for name, s in (snap.get("histograms") or {}).items():
                hists.setdefault(name, []).append(s)
            g = snap.get("gauges") or {}
            per[mid] = {"role": e["role"],
                        "tokens_per_s": g.get("engine.tokens_per_s", 0.0),
                        "pages_in_use": g.get("engine.pages_in_use", 0),
                        "queue_depth": g.get("engine.queue_depth", 0),
                        "degradation_level":
                            g.get("engine.degradation_level", 0)}
        merged = {name: merge_histograms(ss) for name, ss in hists.items()}
        ttft = merged.get("serve.ttft_seconds", {})
        tpot = merged.get("serve.tpot_seconds", {})
        return {
            "members": self.members(),
            "counters": counters,
            "gauges": gauges,
            "histograms": merged,
            "per_replica": per,
            "fleet": {
                "tokens_per_s": sum(p["tokens_per_s"] for p in
                                    per.values()),
                "ttft_p50": ttft.get("p50"), "ttft_p99": ttft.get("p99"),
                "tpot_p50": tpot.get("p50"), "tpot_p99": tpot.get("p99"),
                "pages_in_use": {m: p["pages_in_use"]
                                 for m, p in per.items()},
                "degradation_level": {m: p["degradation_level"]
                                      for m, p in per.items()},
            },
        }

    def to_prometheus(self) -> str:
        """One exposition document for the whole fleet: every member's
        rows re-labeled with ``{role,replica}`` (a member's own labels are
        kept and extended), plus ``fleet_*`` rollup rows. Feed ONE scrape
        target this and Prometheus sees the fleet without per-replica
        service discovery."""
        from paddle_tpu.observability.prometheus import (_labels, _name,
                                                         _value)
        by_name: dict = {}

        def _add(kind, name, line):
            by_name.setdefault((name, kind), []).append(line)

        def _split(flat):
            # undo observability._flatname: "n{k=v,k2=v2}" -> (n, pairs)
            if "{" not in flat:
                return flat, ()
            base, _, inner = flat.partition("{")
            pairs = tuple(tuple(p.split("=", 1))
                          for p in inner.rstrip("}").split(",") if "=" in p)
            return base, pairs

        for mid, e in sorted(self._live().items()):
            snap = e["snapshot"]
            ident = (("role", e["role"]), ("replica", mid))

            def _ident(lk, extra=()):
                # a member's own labels win a name clash (e.g. the
                # router's per-replica series already carry `replica=`)
                own = {k for k, _ in lk}
                return tuple((k, v) for k, v in ident
                             if k not in own) + tuple(extra)

            for kind, key in (("counter", "counters"), ("gauge", "gauges")):
                for flat, v in sorted((snap.get(key) or {}).items()):
                    base, lk = _split(flat)
                    n = _name(base)
                    _add(kind, n,
                         f"{n}{_labels(lk, _ident(lk))} {_value(v)}")
            for flat, s in sorted((snap.get("histograms") or {}).items()):
                base, lk = _split(flat)
                n = _name(base)
                for q, key in ((0.5, "p50"), (0.99, "p99")):
                    if s.get(key) is not None:
                        _add("summary", n,
                             f"{n}{_labels(lk, _ident(lk, (('quantile', q),)))}"
                             f" {_value(s[key])}")
                _add("summary", n,
                     f"{n}_sum{_labels(lk, _ident(lk))} {_value(s['total'])}")
                _add("summary", n,
                     f"{n}_count{_labels(lk, _ident(lk))} "
                     f"{_value(s['count'])}")
        roll = self.rollup()
        _add("gauge", "fleet_members",
             f"fleet_members {_value(len(roll['members']))}")
        _add("gauge", "fleet_tokens_per_s",
             f"fleet_tokens_per_s {_value(roll['fleet']['tokens_per_s'])}")
        for stem in ("ttft", "tpot"):
            for q, key in ((0.5, "p50"), (0.99, "p99")):
                v = roll["fleet"][f"{stem}_{key}"]
                if v is not None:
                    n = f"fleet_{stem}_seconds"
                    _add("summary", n,
                         f"{n}{_labels((), (('quantile', q),))} "
                         f"{_value(v)}")
        out = []
        for (n, kind), lines in sorted(by_name.items()):
            out.append(f"# TYPE {n} {kind}")
            out.extend(lines)
        return "\n".join(out) + ("\n" if out else "")


# ------------------------------------------------------------ HTTP + CLI


def start_fleet_exporter(fleet: FleetMetrics, host="127.0.0.1", port=0,
                         slo=None):
    """Serve the fleet plane over stdlib HTTP from a daemon thread:
    ``GET /metrics`` is `FleetMetrics.to_prometheus`, ``GET /fleet`` (and
    ``/``) the `rollup` JSON. With an `SLOEvaluator` attached (``slo=``),
    ``GET /alerts`` answers its ``alerts_payload()`` (specs + live state
    + the bounded transition ring) and the alert series ride ``/metrics``
    as ``slo_*`` rows. Returns the live ``ThreadingHTTPServer``
    (``.server_address[1]`` is the bound port, ``.shutdown()`` stops
    it)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from paddle_tpu.observability.prometheus import CONTENT_TYPE

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            path = self.path.split("?")[0].rstrip("/")
            if path == "/metrics":
                body = fleet.to_prometheus()
                if slo is not None:
                    body += slo.to_prometheus()
                body = body.encode()
                ctype = CONTENT_TYPE
            elif path == "/alerts" and slo is not None:
                body = json.dumps(slo.alerts_payload(),
                                  sort_keys=True).encode()
                ctype = "application/json"
            elif path in ("", "/fleet"):
                body = json.dumps(fleet.rollup(), sort_keys=True).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # scrapes must not spam stderr
            pass

    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="pt-fleet-exporter")
    t.start()
    return srv


def scrape_once(fleet: FleetMetrics, members: dict, secret=None,
                timeout=5.0) -> int:
    """Pull STATS from every member endpoint and ingest; returns how many
    answered. The standalone CLI's loop body, also handy in tests."""
    ok = 0
    for mid, ep in sorted(members.items()):
        cli = None
        try:
            cli = _wire_client(ep, secret, timeout)
            snap = cli.stats()
        except (OSError, ConnectionError, ValueError, RuntimeError):
            continue
        finally:
            if cli is not None:
                try:
                    cli.close()
                except OSError:
                    pass
        from paddle_tpu.distributed.fleet.elastic import node_role
        fleet.ingest(mid, node_role(mid), ep, snap)
        ok += 1
    return ok


def _resolve_members(args) -> dict:
    members = {}
    for spec in args.member:
        mid, _, ep = spec.partition("=")
        if not ep:
            raise SystemExit(f"--member wants ID=HOST:PORT, got {spec!r}")
        members[mid] = ep
    registry = None
    if args.registry_dir:
        from paddle_tpu.distributed.fleet.elastic import NodeRegistry
        registry = NodeRegistry(args.registry_dir)
    elif args.registry_addr:
        from paddle_tpu.distributed.fleet.elastic import TcpNodeRegistry
        registry = TcpNodeRegistry(args.registry_addr)
    if registry is not None:
        try:
            members.update({rid: str(ep) for rid, ep
                            in registry.alive_nodes().items()})
        except OSError:
            pass
    if not members:
        raise SystemExit("no members: need --member, --registry-dir or "
                         "--registry-addr")
    return members


def main(argv=None):
    ap = argparse.ArgumentParser(
        "paddle_tpu.observability.fleet",
        description="standalone fleet metrics/tracing plane (router-less "
                    "fleets; routered ones get this via --fleet-port)")
    ap.add_argument("--registry-dir", default=None,
                    help="shared-filesystem elastic registry to enumerate")
    ap.add_argument("--registry-addr", default=None,
                    help="host:port of a TcpRegistryServer to enumerate")
    ap.add_argument("--member", action="append", default=[],
                    metavar="ID=HOST:PORT",
                    help="static member entry (repeatable; composes with "
                         "the registry)")
    ap.add_argument("--secret", default=None,
                    help="fleet-shared serve auth secret (default "
                         "PADDLE_SERVE_TOKEN)")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="scrape interval seconds")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port for /metrics + /fleet")
    ap.add_argument("--slo", action="append", default=[],
                    metavar="NAME=OBJECTIVE",
                    help="fleet-scope SLO evaluated over the rollup each "
                         "scrape, e.g. 'ttft=serve.ttft_seconds p99 < "
                         "2.0s;fast=60;slow=300' (repeatable; alerts on "
                         "GET /alerts — docs/OBSERVABILITY.md)")
    ap.add_argument("--once", action="store_true",
                    help="one scrape, print the rollup JSON, exit")
    ap.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="one-shot: pull TRACE_ID from every member, "
                         "print the stitched Chrome trace JSON, exit")
    args = ap.parse_args(argv)
    members = _resolve_members(args)
    if args.trace:
        col = TraceCollector(members, secret=args.secret)
        print(json.dumps(col.collect(args.trace)))
        return
    fleet = FleetMetrics(ttl_s=max(30.0, 6 * args.interval))
    if args.once:
        scrape_once(fleet, members, secret=args.secret)
        print(json.dumps(fleet.rollup(), indent=2, sort_keys=True))
        return
    slo = None
    if args.slo:
        from paddle_tpu.observability.slo import SLOEvaluator, parse_slo
        slo = SLOEvaluator([parse_slo(s) for s in args.slo], scope="fleet")
    srv = start_fleet_exporter(fleet, host=args.host, port=args.port,
                               slo=slo)
    print(f"FLEET {srv.server_address[1]}", flush=True)
    try:
        while True:
            scrape_once(fleet, members, secret=args.secret)
            if slo is not None:
                slo.evaluate(fleet.rollup())
            time.sleep(args.interval)
    except KeyboardInterrupt:
        srv.shutdown()


if __name__ == "__main__":
    main()
