"""SLO engine: declarative objectives judged over metric-snapshot deltas.

The registry (`observability/__init__.py`) and the fleet plane
(`observability/fleet.py`) *measure*; this module *judges*. An
:class:`SLOSpec` declares one objective in the shapes production serving
actually promises:

- a histogram percentile target — ``serve.ttft_seconds p99 < 2.0s``
  (also ``p50`` and ``mean``);
- an error-ratio target — ``serve.request_errors / serve.requests < 0.1%``.

:class:`SLOEvaluator` evaluates a list of specs against successive
**snapshots** (``metrics.snapshot()`` dicts, or `FleetMetrics.rollup()`
bodies — both expose the same ``count/total/p50/p99`` histogram summary
keys, so ONE evaluator serves both scopes). Windowed burn rates come from
**differencing** snapshots: the registry's counters and histogram
count/total are cumulative, so the value over a window is the delta
between now and the newest sample at least that old — exactly how
`FleetMetrics` already ingests members. Nothing here polls, sleeps, or
owns a thread: callers (serve's stats loop, the router's poll loop,
tests) call :meth:`SLOEvaluator.evaluate` on their own cadence with an
optional explicit ``now``, so every lifecycle test is deterministic with
zero sleeps (the same injectable-clock idiom as ``Watchdog.check``).

Alerting is the multi-window burn-rate scheme (the SRE-workbook shape):
an objective breaches only when BOTH a fast window (catches sudden
burns) and a slow window (suppresses blips) exceed ``burn x threshold``,
then walks a pending -> firing -> resolved state machine with dwell-time
hysteresis on both edges (``pending_for_s`` before firing,
``clear_for_s`` before resolving). Transitions land on a bounded alert
ring, the process flight recorder, and ``slo.*`` metrics; `/alerts` on
the fleet HTTP port and :func:`active_alerts` (the watchdog's stall-dump
hook) read them back.

Stdlib-only, like everything under ``observability/``.
"""
from __future__ import annotations

import collections
import re
import threading
import time
import weakref

from paddle_tpu.observability import metrics

__all__ = ["SLOSpec", "SLOEvaluator", "parse_slo", "active_alerts",
           "recent_events"]

# every live evaluator, so the watchdog stall dump can answer "what was
# the fleet promising when it froze" without plumbing references around
_EVALUATORS: "weakref.WeakSet[SLOEvaluator]" = weakref.WeakSet()

_RATIO_RE = re.compile(
    r"^\s*([\w.{}=,\-]+)\s*/\s*([\w.{}=,\-]+)\s*<\s*"
    r"([0-9.eE+\-]+)\s*(%?)\s*$")
_POINT_RE = re.compile(
    r"^\s*([\w.{}=,\-]+)\s+(p50|p99|mean)\s*<\s*"
    r"([0-9.eE+\-]+)\s*(s?)\s*$")


class SLOSpec:
    """One declarative objective.

    name          : alert identity (rides events, metrics labels, /alerts)
    objective     : the human-readable contract string (kept verbatim)
    kind          : 'ratio' | 'percentile' | 'mean'
    metric        : histogram name ('percentile'/'mean' kinds)
    num / den     : counter names ('ratio' kind)
    quantile      : 'p50' | 'p99' ('percentile' kind)
    threshold     : objective bound, post-'%'-scaling
    fast_window_s / slow_window_s : the two burn windows
    burn          : burn-rate multiplier — breach when value >
                    burn * threshold on BOTH windows (1.0 = the bound
                    itself)
    pending_for_s : breach dwell before pending promotes to firing
    clear_for_s   : clean dwell before firing resolves
    """

    __slots__ = ("name", "objective", "kind", "metric", "num", "den",
                 "quantile", "threshold", "fast_window_s", "slow_window_s",
                 "burn", "pending_for_s", "clear_for_s")

    def __init__(self, name, objective, kind, threshold, metric=None,
                 num=None, den=None, quantile=None, fast_window_s=60.0,
                 slow_window_s=300.0, burn=1.0, pending_for_s=0.0,
                 clear_for_s=0.0):
        self.name = str(name)
        self.objective = str(objective)
        self.kind = kind
        self.metric = metric
        self.num = num
        self.den = den
        self.quantile = quantile
        self.threshold = float(threshold)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn = float(burn)
        self.pending_for_s = float(pending_for_s)
        self.clear_for_s = float(clear_for_s)
        if self.threshold <= 0:
            raise ValueError(f"SLO {name!r}: threshold must be > 0")
        if self.fast_window_s > self.slow_window_s:
            raise ValueError(f"SLO {name!r}: fast window must be <= slow")

    @classmethod
    def parse(cls, name, objective, **kw):
        """Parse an objective string.

        ``serve.ttft_seconds p99 < 2.0s`` -> percentile target (trailing
        ``s`` optional); ``serve.request_errors / serve.requests < 0.1%``
        -> error-ratio target (``%`` divides the bound by 100). ``p50``,
        ``p99`` and ``mean`` are the supported points — the registry's
        bounded reservoir only surfaces those.
        """
        m = _RATIO_RE.match(objective)
        if m:
            num, den, bound, pct = m.groups()
            thr = float(bound) / (100.0 if pct else 1.0)
            return cls(name, objective, "ratio", thr, num=num, den=den,
                       **kw)
        m = _POINT_RE.match(objective)
        if m:
            metric, point, bound, _unit = m.groups()
            kind = "mean" if point == "mean" else "percentile"
            q = None if point == "mean" else point
            return cls(name, objective, kind, float(bound), metric=metric,
                       quantile=q, **kw)
        raise ValueError(
            f"unparseable SLO objective {objective!r} — expected "
            f"'<hist> p50|p99|mean < <bound>[s]' or "
            f"'<counter> / <counter> < <bound>[%]'")

    def to_dict(self):
        return {"name": self.name, "objective": self.objective,
                "kind": self.kind, "threshold": self.threshold,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s, "burn": self.burn,
                "pending_for_s": self.pending_for_s,
                "clear_for_s": self.clear_for_s}


def parse_slo(text):
    """CLI/config form: ``name=<objective>[;fast=60][;slow=300][;burn=1]
    [;pending=0][;clear=0]`` -> :class:`SLOSpec` (the ``--slo`` flag on
    serve and the router)."""
    head, _, opts = str(text).partition(";")
    name, sep, objective = head.partition("=")
    if not sep or not name.strip() or not objective.strip():
        raise ValueError(f"--slo needs 'name=<objective>', got {text!r}")
    kw = {}
    keys = {"fast": "fast_window_s", "slow": "slow_window_s",
            "burn": "burn", "pending": "pending_for_s",
            "clear": "clear_for_s"}
    for part in filter(None, (p.strip() for p in opts.split(";"))):
        k, sep, v = part.partition("=")
        if not sep or k.strip() not in keys:
            raise ValueError(f"unknown SLO option {part!r} in {text!r}")
        kw[keys[k.strip()]] = float(v)
    return SLOSpec.parse(name.strip(), objective.strip(), **kw)


def _read_cum(spec, snapshot):
    """The spec's CUMULATIVE reading from one snapshot: a tuple whose
    element-wise deltas over a window yield the windowed value."""
    if spec.kind == "ratio":
        ctr = snapshot.get("counters", {})
        return (float(ctr.get(spec.num, 0) or 0),
                float(ctr.get(spec.den, 0) or 0))
    s = snapshot.get("histograms", {}).get(spec.metric)
    if not s:
        return (0.0, 0.0)
    count = float(s.get("count", 0) or 0)
    if spec.kind == "mean":
        return (count, float(s.get("total", 0) or 0))
    # percentile: the reservoir reading is already windowed-recent; the
    # cumulative count gates it on "did traffic actually land in the
    # window" so a stale reading can't fire into silence
    reading = s.get(spec.quantile)
    return (count, float(reading) if reading is not None else None)


def _window_value(spec, samples, now, window_s):
    """Value of the spec over the trailing ``window_s``: delta between
    the newest sample and the newest sample at least ``window_s`` old.
    ``None`` = window unknown (no old-enough reference, or no traffic) —
    the conservative no-fire reading."""
    ref = None
    for t, cum in reversed(samples):
        if t <= now - window_s:
            ref = cum
            break
    if ref is None:
        return None
    cur = samples[-1][1]
    if spec.kind == "ratio":
        dden = cur[1] - ref[1]
        if dden <= 0:
            return None
        return max(0.0, cur[0] - ref[0]) / dden
    if spec.kind == "mean":
        dcount = cur[0] - ref[0]
        if dcount <= 0:
            return None
        return max(0.0, cur[1] - ref[1]) / dcount
    # percentile: gate the current reservoir reading on window traffic
    if cur[0] - ref[0] <= 0 or cur[1] is None:
        return None
    return cur[1]


class SLOEvaluator:
    """Evaluates specs against successive snapshots; owns no thread.

    registry : snapshot source when ``evaluate()`` gets none (default the
               process registry); fleet-scope callers pass rollups
               explicitly and leave this alone
    scope    : label riding alerts/metrics ('process' | 'fleet' | ...)
    clock    : default ``now`` source (``time.monotonic``); tests inject
               explicit ``now=`` instead and never sleep
    ring     : bounded alert-event history kept for /alerts + stall dumps
    """

    def __init__(self, specs, registry=None, scope="process", clock=None,
                 ring=128):
        self.specs = list(specs)
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.registry = registry
        self.scope = str(scope)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._samples = {s.name: collections.deque() for s in self.specs}
        self._state = {s.name: {"state": "ok", "breach_since": None,
                                "clear_since": None, "fired_at": None,
                                "value_fast": None, "value_slow": None}
                       for s in self.specs}
        self.events = collections.deque(maxlen=int(ring))
        self._m_fired = metrics.counter("slo.alerts_fired")
        self._m_resolved = metrics.counter("slo.alerts_resolved")
        self._m_evals = metrics.counter("slo.evaluations")
        _EVALUATORS.add(self)

    # ------------------------------------------------------------ evaluation

    def evaluate(self, snapshot=None, now=None):
        """One evaluation pass; returns the per-spec status list.

        ``snapshot`` defaults to ``registry.snapshot()`` (the process
        registry when none was given); fleet callers pass the rollup.
        ``now`` defaults to the evaluator's clock — pass explicit values
        for deterministic lifecycle tests.
        """
        if snapshot is None:
            reg = self.registry
            if reg is None:
                reg = metrics
            snapshot = reg.snapshot()
        now = float(self._clock() if now is None else now)
        out = []
        with self._lock:
            self._m_evals.inc()
            for spec in self.specs:
                out.append(self._eval_one(spec, snapshot, now))
        return out

    def _eval_one(self, spec, snapshot, now):
        samples = self._samples[spec.name]
        samples.append((now, _read_cum(spec, snapshot)))
        # prune: drop samples that can no longer be any window's
        # reference — everything older than the newest sample that is
        # itself older than the slow window
        while len(samples) >= 2 and samples[1][0] <= now - spec.slow_window_s:
            samples.popleft()

        v_fast = _window_value(spec, samples, now, spec.fast_window_s)
        v_slow = _window_value(spec, samples, now, spec.slow_window_s)
        bound = spec.burn * spec.threshold
        breaching = (v_fast is not None and v_fast > bound
                     and v_slow is not None and v_slow > bound)

        st = self._state[spec.name]
        st["value_fast"], st["value_slow"] = v_fast, v_slow
        if breaching:
            st["clear_since"] = None
            if st["state"] == "ok":
                st["state"] = "pending"
                st["breach_since"] = now
            if st["state"] == "pending" \
                    and now - st["breach_since"] >= spec.pending_for_s:
                st["state"] = "firing"
                st["fired_at"] = now
                self._transition(spec, st, now, "firing")
        else:
            st["breach_since"] = None if st["state"] != "firing" else \
                st["breach_since"]
            if st["state"] == "pending":
                st["state"] = "ok"
            elif st["state"] == "firing":
                if st["clear_since"] is None:
                    st["clear_since"] = now
                if now - st["clear_since"] >= spec.clear_for_s:
                    st["state"] = "ok"
                    st["breach_since"] = None
                    st["clear_since"] = None
                    self._transition(spec, st, now, "resolved")
        metrics.gauge("slo.alert_firing", slo=spec.name,
                      scope=self.scope).set(
                          1 if st["state"] == "firing" else 0)
        if v_fast is not None:
            metrics.gauge("slo.burn_rate", slo=spec.name, scope=self.scope,
                          window="fast").set(v_fast / spec.threshold)
        if v_slow is not None:
            metrics.gauge("slo.burn_rate", slo=spec.name, scope=self.scope,
                          window="slow").set(v_slow / spec.threshold)
        return self._status(spec, st)

    def _transition(self, spec, st, now, state):
        ev = {"t": now, "slo": spec.name, "scope": self.scope,
              "state": state, "objective": spec.objective,
              "threshold": spec.threshold,
              "value_fast": st["value_fast"],
              "value_slow": st["value_slow"]}
        self.events.append(ev)
        (self._m_fired if state == "firing" else self._m_resolved).inc()
        try:
            from paddle_tpu.observability.flight_recorder import flight
            flight.record("slo_alert", slo=spec.name, state=state,
                          scope=self.scope, objective=spec.objective,
                          value_fast=st["value_fast"],
                          value_slow=st["value_slow"])
        except Exception:  # noqa: BLE001 — alerting must not take the loop
            pass

    def _status(self, spec, st):
        return {"slo": spec.name, "scope": self.scope,
                "state": st["state"], "objective": spec.objective,
                "threshold": spec.threshold,
                "value_fast": st["value_fast"],
                "value_slow": st["value_slow"],
                "breach_since": st["breach_since"],
                "fired_at": st["fired_at"] if st["state"] == "firing"
                else None}

    # -------------------------------------------------------------- readback

    def active(self):
        """Currently-FIRING alerts (the /alerts + stall-dump payload)."""
        with self._lock:
            return [self._status(s, self._state[s.name])
                    for s in self.specs
                    if self._state[s.name]["state"] == "firing"]

    def status(self):
        """All specs' current status, firing or not."""
        with self._lock:
            return [self._status(s, self._state[s.name])
                    for s in self.specs]

    def history(self, n=None):
        with self._lock:
            evs = list(self.events)
        return evs if n is None else evs[-int(n):]

    def alerts_payload(self):
        """The GET /alerts body: specs + live status + transition ring."""
        return {"scope": self.scope,
                "specs": [s.to_dict() for s in self.specs],
                "active": self.active(),
                "status": self.status(),
                "history": self.history()}

    def to_prometheus(self):
        """Alert state as exposition lines (appended to the fleet
        exporter's /metrics body — names pre-sanitized, no registry
        round-trip so a fleet-scope evaluator exports even when its
        snapshots come from rollups)."""
        from paddle_tpu.observability.prometheus import _labels, _value
        lines = ["# TYPE slo_alert_firing gauge"]
        for s in self.status():
            lab = _labels((("scope", s["scope"]), ("slo", s["slo"])))
            lines.append(
                f"slo_alert_firing{lab} "
                f"{_value(1 if s['state'] == 'firing' else 0)}")
        burn = ["# TYPE slo_burn_rate gauge"]
        for s in self.status():
            for win, key in (("fast", "value_fast"), ("slow", "value_slow")):
                if s[key] is None:
                    continue
                lab = _labels((("scope", s["scope"]), ("slo", s["slo"]),
                               ("window", win)))
                burn.append(f"slo_burn_rate{lab} "
                            f"{_value(s[key] / s['threshold'])}")
        if len(burn) > 1:
            lines.extend(burn)
        return "\n".join(lines) + "\n"


def active_alerts():
    """Firing alerts across EVERY live evaluator in this process — the
    watchdog stall dump's 'what was the fleet promising' hook."""
    out = []
    for ev in list(_EVALUATORS):
        try:
            out.extend(ev.active())
        except Exception:  # noqa: BLE001 — dumps must never fail
            pass
    return out


def recent_events(n=32):
    """Most recent alert transitions across every live evaluator,
    time-ordered."""
    evs = []
    for ev in list(_EVALUATORS):
        try:
            evs.extend(ev.history())
        except Exception:  # noqa: BLE001
            pass
    evs.sort(key=lambda e: e.get("t", 0))
    return evs[-int(n):]
