"""Request-scoped tracing: where did THIS request's latency go?

The registry (`observability/__init__.py`) answers process-wide questions;
serving SLOs need per-request ones — queue wait vs prefill vs decode, TTFT
and TPOT percentiles (the serving literature's primary metrics: Ragged
Paged Attention, arxiv 2604.15464, reports per-sequence TTFT/TPOT; the
Gemma-on-TPU comparison, arxiv 2605.25645, frames serving results as
latency-percentile SLOs).

One :class:`RequestTrace` rides each request from wire-accept
(`inference/serve.py`) or `DecodeEngine.submit` through admission, prefill,
decode and retirement. Each phase transition:

- records a span on the registry's Chrome-trace ring with the shared
  ``request_id`` in the event ``args`` — load the export in Perfetto and
  filter/group by ``request_id`` to see one request's whole life;
- feeds the derived SLO histograms the STATS op, ``to_prometheus()``, and
  `bench.py --smoke` expose:

  | histogram            | meaning                                        |
  |----------------------|------------------------------------------------|
  | `serve.ttft_seconds` | accept -> first generated token (TTFT)         |
  | `serve.tpot_seconds` | per-output-token time AFTER the first (TPOT):  |
  |                      | (t_done - t_first) / (n_tokens - 1) per request|
  | `serve.e2e_seconds`  | accept -> retirement                           |

Phase marks are monotonic (`time.perf_counter`) and each transition is
idempotent-guarded, so double-marking (e.g. EOS retire during harvest of an
already-done fifo entry) cannot double-count a histogram.

Stdlib-only, like everything under ``observability/``.
"""
from __future__ import annotations

import itertools
import os
import struct
import threading
import time

from paddle_tpu.observability import _EPOCH, metrics

__all__ = ["RequestTrace", "new_request_id", "mint_trace", "new_span_id",
           "trace_to_words", "words_to_trace", "TRACE_WORDS"]

_ids = itertools.count(1)


def new_request_id() -> str:
    """Process-unique monotonic request id (``req-<n>``); `itertools.count`
    is atomic under the GIL, so ids are unique across submitter threads."""
    return f"req-{next(_ids)}"


# ------------------------------------------------------------- fleet context
#
# A fleet trace context is a 16-byte random trace id plus the 8-byte span id
# of the upstream hop, minted once at ingress (`RemotePredictor.generate` or
# the router) and threaded through every wire hop. On the wire it rides as
# six little-endian int32 words appended to the existing int32 options
# vectors (GENERATE/PREFILL/KV_STREAM) — all-zero words mean "no trace",
# which a random 128-bit id never collides with in practice.

TRACE_WORDS = 6  # 4 words trace id + 2 words parent span id


def mint_trace() -> tuple[str, str]:
    """New (trace_id, span_id) hex pair for an ingress request."""
    return os.urandom(16).hex(), os.urandom(8).hex()


def new_span_id() -> str:
    """Fresh 8-byte span id (hex) for one process's hop within a trace."""
    return os.urandom(8).hex()


def trace_to_words(trace_id: str | None, parent: str | None) -> list[int]:
    """Encode a (trace_id, parent span) hex context as TRACE_WORDS signed
    int32 words for the wire options vectors. ``None`` encodes as zeros."""
    tb = bytes.fromhex(trace_id) if trace_id else b"\x00" * 16
    pb = bytes.fromhex(parent) if parent else b"\x00" * 8
    return list(struct.unpack("<4i", tb)) + list(struct.unpack("<2i", pb))


def words_to_trace(words) -> tuple[str | None, str | None]:
    """Decode TRACE_WORDS int32 words back to (trace_id, parent) hex;
    all-zero groups decode to ``None``."""
    tb = struct.pack("<4i", *(int(w) for w in words[:4]))
    pb = struct.pack("<2i", *(int(w) for w in words[4:6]))
    return (tb.hex() if any(tb) else None,
            pb.hex() if any(pb) else None)


class RequestTrace:
    """Phase marks for one generation request.

    Lifecycle (each mark records the span it closes):

        accept ──queue──> admitted ──prefill──> first_token ──decode──> done
           └────────────────────── e2e ───────────────────────────────────┘

    ``accept`` is wire-accept when serve creates the trace, or submit time
    when the engine creates it (`DecodeEngine.submit` with no trace given).
    """

    __slots__ = ("request_id", "t_accept", "t_submit", "t_admit",
                 "t_first_token", "t_done", "n_tokens", "error", "_lock",
                 "trace_id", "parent_span", "span_id")

    def __init__(self, request_id: str | None = None,
                 trace_id: str | None = None, parent_span: str | None = None):
        self.request_id = request_id or new_request_id()
        self._lock = threading.Lock()
        self.t_accept = time.perf_counter()
        self.t_submit = None
        self.t_admit = None
        self.t_first_token = None
        self.t_done = None
        self.n_tokens = 0
        self.error = None
        # fleet trace context (hex strings); absent on local-only requests
        self.trace_id = trace_id
        self.parent_span = parent_span
        self.span_id = new_span_id() if trace_id else None

    def attach_context(self, trace_id: str | None,
                       parent_span: str | None = None):
        """Adopt a wire-carried trace context AFTER construction (serve
        creates the trace before the options vector is parsed). Idempotent;
        a no-op when no context rode the request."""
        if trace_id and self.trace_id is None:
            self.trace_id = trace_id
            self.parent_span = parent_span
            self.span_id = new_span_id()

    # ------------------------------------------------------------ phase marks

    def _span(self, phase, t0, t1):
        metrics.add_span(f"request.{phase}", t0, max(0.0, t1 - t0),
                         cat="request", args={"request_id": self.request_id},
                         trace_id=self.trace_id, parent=self.parent_span,
                         span_id=self.span_id)

    def mark_submit(self):
        """Entered the scheduler queue (engine submit)."""
        if self.t_submit is None:
            self.t_submit = time.perf_counter()

    def mark_admitted(self):
        """Left the queue: slot + pages assigned, prefill about to run.
        Only the per-request span lands here — the aggregate queue-wait
        histogram already exists as `engine.queue_wait_seconds`."""
        if self.t_admit is not None:
            return
        self.t_admit = time.perf_counter()
        t0 = self.t_submit if self.t_submit is not None else self.t_accept
        self._span("queue", t0, self.t_admit)

    def mark_first_token(self):
        """Prefill produced the first generated token — the TTFT moment."""
        if self.t_first_token is not None:
            return
        self.t_first_token = time.perf_counter()
        self.n_tokens = max(self.n_tokens, 1)
        self._span("prefill", self.t_admit if self.t_admit is not None
                   else self.t_accept, self.t_first_token)
        metrics.histogram("serve.ttft_seconds").observe(
            self.t_first_token - self.t_accept)

    def mark_tokens(self, n=1):
        """``n`` more generated tokens delivered (decode harvest)."""
        self.n_tokens += int(n)

    def mark_done(self, error: str | None = None):
        """Retired (delivered, EOS, or failed): closes decode + e2e spans
        and lands the per-request TPOT/e2e observations. The done
        transition is locked — the engine thread (retirement) and a serve
        connection thread (result timeout) can race to close the same
        trace, and exactly one of them may account it."""
        with self._lock:
            if self.t_done is not None:
                return
            self.t_done = time.perf_counter()
            self.error = error
        if self.t_first_token is not None:
            self._span("decode", self.t_first_token, self.t_done)
        self._span("e2e", self.t_accept, self.t_done)
        if error is None:
            # SLO histograms take SUCCESSFUL requests only: an aborted
            # request's t_done is stamped whenever the failure surfaced,
            # and one stall must not corrupt the TPOT/e2e percentiles
            if self.t_first_token is not None and self.n_tokens > 1:
                metrics.histogram("serve.tpot_seconds").observe(
                    (self.t_done - self.t_first_token)
                    / (self.n_tokens - 1))
            metrics.histogram("serve.e2e_seconds").observe(
                self.t_done - self.t_accept)
        else:
            metrics.counter("serve.request_errors").inc()

    # --------------------------------------------------------------- exports

    @property
    def done(self) -> bool:
        return self.t_done is not None

    def phase(self) -> str:
        if self.t_done is not None:
            return "done" if self.error is None else "error"
        if self.t_first_token is not None:
            return "decode"
        if self.t_admit is not None:
            return "prefill"
        if self.t_submit is not None:
            return "queued"
        return "accepted"

    def to_dict(self) -> dict:
        """JSON-ready record (watchdog dumps, debugging). Times are
        process-epoch-relative seconds, matching the Chrome-trace ring."""
        d = {"request_id": self.request_id, "phase": self.phase(),
             "n_tokens": self.n_tokens, "error": self.error,
             "trace_id": self.trace_id, "parent": self.parent_span}
        for k in ("t_accept", "t_submit", "t_admit", "t_first_token",
                  "t_done"):
            v = getattr(self, k)
            # same epoch as the span ring (seconds vs its microseconds), so
            # a watchdog dump's times line up with the exported Chrome trace
            d[k] = round(v - _EPOCH, 6) if v is not None else None
        return d
