"""Bench regression ledger: does the newest BENCH run regress the best?

The repo accumulates ``BENCH_rNN.json`` artifacts (one per bench run:
``{n, cmd, rc, tail, parsed}`` where ``tail`` holds the run's stdout tail
— including every rung's single-line JSON emission — and ``parsed`` is
the last such line). The trajectory had no reader; this module is it:

    python -m paddle_tpu.observability.regress [DIR] [--tolerance 0.05]

reads every artifact in DIR, extracts each run's per-rung headline
metrics (any emitted line with ``metric``/``value``), compares the
NEWEST run against the BEST prior value per metric, and prints ONE
single-line JSON verdict::

    {"ok": true|false, "newest": N,
     "regressions": [{"metric", "value", "best", "best_run", "unit",
                      "ratio"}],
     "skipped": [{"note", ...}]}

Direction comes from the metric's ``unit``: rates (``.../s``) regress
DOWN, times (``s``/``seconds``/``ms``) regress UP; other units are
skipped with a note. Anything unreadable — a missing directory, corrupt
JSON, an ``rc != 0`` run, a rung that emitted ``ok: false`` — lands in
``skipped`` rather than crashing, matching bench.py's crash-proof
emission discipline. Exit code 1 iff regressions were found.

Stdlib-only, like everything under ``observability/``.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

__all__ = ["load_runs", "extract_metrics", "compare", "main"]

_RUN_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _direction(unit):
    """+1 = higher is better, -1 = lower is better, None = unknown."""
    u = str(unit or "").strip().lower()
    if u.endswith("/s") or u.endswith("/sec"):
        return 1
    if u in ("s", "sec", "seconds", "ms", "us"):
        return -1
    return None


def load_runs(dirpath, pattern="BENCH_r*.json"):
    """-> (runs, skipped): runs is ``[(run_no, artifact_dict)]`` sorted by
    run number; unreadable artifacts become skip notes."""
    runs, skipped = [], []
    try:
        paths = sorted(glob.glob(os.path.join(dirpath, pattern)))
    except Exception as e:  # noqa: BLE001
        return [], [{"note": f"unreadable dir {dirpath}: "
                             f"{type(e).__name__}: {e}"}]
    for path in paths:
        m = _RUN_RE.search(os.path.basename(path))
        if not m:
            skipped.append({"note": f"unrecognized name {path}"})
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception as e:  # noqa: BLE001
            skipped.append({"note": f"corrupt artifact {path}: "
                                    f"{type(e).__name__}: {e}"})
            continue
        if not isinstance(data, dict):
            skipped.append({"note": f"not an artifact dict: {path}"})
            continue
        runs.append((int(m.group(1)), data))
    runs.sort(key=lambda r: r[0])
    return runs, skipped


def extract_metrics(run_no, artifact):
    """-> (metrics, skipped): ``{metric_name: (value, unit)}`` from every
    rung emission recoverable from the artifact's ``tail`` (fallback: the
    single ``parsed`` record). Rungs that emitted ``ok: false`` skip with
    a note — a failed rung's number is noise, not a baseline."""
    metrics, skipped = {}, []
    records = []
    tail = artifact.get("tail") or ""
    for line in str(tail).splitlines():
        line = line.strip()
        if not line.startswith("{") or not line.endswith("}"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            records.append(rec)
    if not records and isinstance(artifact.get("parsed"), dict) \
            and "metric" in artifact["parsed"]:
        records.append(artifact["parsed"])
    if not records:
        skipped.append({"note": f"run {run_no}: no rung emissions "
                                f"(rc={artifact.get('rc')}, parsed="
                                f"{artifact.get('parsed') is not None})"})
        return metrics, skipped
    for rec in records:
        name = rec.get("metric")
        value = rec.get("value")
        if rec.get("ok") is False:
            skipped.append({"note": f"run {run_no}: rung {name} "
                                    f"emitted ok:false"})
            continue
        if not isinstance(value, (int, float)) or value != value:
            skipped.append({"note": f"run {run_no}: rung {name} has "
                                    f"non-numeric value {value!r}"})
            continue
        # last emission wins a duplicate name within one run (re-runs)
        metrics[str(name)] = (float(value), rec.get("unit"))
    return metrics, skipped


def compare(runs, tolerance=0.05):
    """The verdict dict for a ``[(run_no, {metric: (value, unit)})]``
    history: newest run vs the best prior value per metric."""
    verdict = {"ok": True, "newest": None, "regressions": [], "skipped": []}
    if not runs:
        verdict["skipped"].append({"note": "no runs found"})
        return verdict
    newest_no, newest = runs[-1]
    verdict["newest"] = newest_no
    priors = runs[:-1]
    if not priors:
        verdict["skipped"].append(
            {"note": f"run {newest_no}: no prior run to compare against"})
        return verdict
    for name, (value, unit) in sorted(newest.items()):
        sign = _direction(unit)
        if sign is None:
            verdict["skipped"].append(
                {"note": f"{name}: unknown unit {unit!r} — no direction"})
            continue
        best = best_run = None
        for no, m in priors:
            if name not in m:
                continue
            v = m[name][0]
            if best is None or (v - best) * sign > 0:
                best, best_run = v, no
        if best is None:
            verdict["skipped"].append(
                {"note": f"{name}: no prior run carries it"})
            continue
        if best == 0:
            verdict["skipped"].append(
                {"note": f"{name}: best prior is 0 — ratio undefined"})
            continue
        ratio = value / best
        regressed = ratio < (1.0 - tolerance) if sign > 0 \
            else ratio > (1.0 + tolerance)
        if regressed:
            verdict["regressions"].append(
                {"metric": name, "value": value, "best": best,
                 "best_run": best_run, "unit": unit,
                 "ratio": round(ratio, 4)})
    verdict["ok"] = not verdict["regressions"]
    return verdict


def run_ledger(dirpath, tolerance=0.05, pattern="BENCH_r*.json"):
    """Load + extract + compare; never raises."""
    try:
        raw_runs, skipped = load_runs(dirpath, pattern)
        runs = []
        for no, artifact in raw_runs:
            m, sk = extract_metrics(no, artifact)
            skipped.extend(sk)
            if m:
                runs.append((no, m))
        verdict = compare(runs, tolerance=tolerance)
        verdict["skipped"] = skipped + verdict["skipped"]
        return verdict
    except Exception as e:  # noqa: BLE001 — the ledger never crashes
        return {"ok": True, "newest": None, "regressions": [],
                "skipped": [{"note": f"ledger failed: "
                                     f"{type(e).__name__}: {e}"}]}


def main(argv=None):
    ap = argparse.ArgumentParser("paddle_tpu.observability.regress")
    ap.add_argument("dir", nargs="?", default=".",
                    help="directory holding BENCH_r*.json artifacts")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="allowed fractional slack vs the best prior "
                         "run before a metric counts as regressed")
    ap.add_argument("--pattern", default="BENCH_r*.json")
    args = ap.parse_args(argv)
    verdict = run_ledger(args.dir, tolerance=args.tolerance,
                         pattern=args.pattern)
    print(json.dumps(verdict), flush=True)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
