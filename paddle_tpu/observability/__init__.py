"""Process-wide runtime telemetry: the metrics registry every layer reports to.

The reference ships per-subsystem introspection (profiler CUPTI tables, the
`flops` API, DataLoader worker logs); this build centralizes it: one
thread-safe, zero-dependency registry of counters / gauges / histograms that
the hot layers (jit capture, collectives, pipeline engines, DataLoader,
inference serving, decode) write into, and that `paddle.profiler`, the hapi
VisualDL callback, `bench.py`, and the serve stats endpoint all read from.

Design:
- **Counter** — monotonically increasing float/int (`inc`).
- **Gauge** — last-write-wins scalar (`set`).
- **Histogram** — count/sum/min/max plus a bounded reservoir of recent
  observations for p50/p99; `time()` returns a context manager that
  observes the elapsed seconds AND records a span for Chrome-trace export.
- Metrics are keyed by ``(name, sorted(labels))``; the flat snapshot key is
  ``name{k=v,...}`` (Prometheus-style).
- ``snapshot()`` → plain dict (JSON-ready); ``to_json()`` serializes it;
  ``chrome_trace()`` / ``export_chrome_trace(path)`` emit the recorded spans
  in Chrome ``traceEvents`` format (load with `chrome://tracing`, Perfetto,
  or `paddle.profiler.load_profiler_result`).

Everything here is stdlib-only ON PURPOSE: instrumented modules import this
at module scope, so it must never create an import cycle or pull in jax.

Semantics note for in-graph instrumentation: counters incremented inside a
jax trace (e.g. `collective.bytes` for the lax.psum path) count **trace-time
insertions**, not device executions — one per compiled program, not one per
step. Eager-path counters count real calls. `docs/OBSERVABILITY.md` carries
the full metric inventory.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "metrics",
    "counter", "gauge", "histogram", "timer", "snapshot", "reset",
    "chrome_trace", "export_chrome_trace", "to_prometheus",
    "set_node_identity", "node_identity", "spans_for_trace",
]

# perf_counter origin for span timestamps — one epoch per process so spans
# from every subsystem land on a shared timeline
_EPOCH = time.perf_counter()
# wall clock at the same instant: per-trace span exports are rebased onto
# unix time so the fleet collector can stitch spans from MANY processes
# (each with its own perf_counter origin) onto one timeline
_EPOCH_UNIX_US = time.time() * 1e6

_RESERVOIR = 512       # recent observations kept per histogram (percentiles)
_MAX_SPANS = 20000     # bounded span ring: old spans drop, process never grows
_MAX_TRACES = 64       # per-trace span rings kept (LRU; fleet TRACE_EXPORT)
_MAX_TRACE_SPANS = 256  # spans kept per traced request
_MAX_LABELED_SERIES = 256  # LRU cap on LABELED series (membership churn)


def _pct_index(n: int, q: float) -> int:
    """Clamped nearest-rank reservoir index for the q-th percentile of n
    sorted values — the ONE place the index math lives, so
    ``Histogram.percentile`` and ``summary()`` can never drift."""
    return min(n - 1, max(0, int(round(q / 100.0 * (n - 1)))))


def _labelkey(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _flatname(name: str, labelkey: tuple) -> str:
    if not labelkey:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labelkey)
    return f"{name}{{{inner}}}"


class Counter:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def reset(self):
        with self._lock:
            self._value = 0

    @property
    def value(self):
        return self._value


class Gauge:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    def reset(self):
        with self._lock:
            self._value = 0

    @property
    def value(self):
        return self._value


class Histogram:
    """count/sum/min/max + bounded reservoir of the most recent observations
    (enough for p50/p99 on step-time-scale series without unbounded memory)."""

    __slots__ = ("_lock", "count", "total", "min", "max", "_recent")

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._recent = collections.deque(maxlen=_RESERVOIR)

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self._recent.append(v)

    def reset(self):
        with self._lock:
            self.count = 0
            self.total = 0.0
            self.min = self.max = None
            self._recent.clear()

    def percentile(self, q):
        with self._lock:
            vals = sorted(self._recent)
        if not vals:
            return None
        return vals[_pct_index(len(vals), q)]

    def summary(self):
        with self._lock:
            vals = sorted(self._recent)
            count, total, mn, mx = self.count, self.total, self.min, self.max
        out = {"count": count, "total": total, "min": mn, "max": mx,
               "mean": (total / count) if count else None}
        if vals:
            out["p50"] = vals[_pct_index(len(vals), 50.0)]
            out["p99"] = vals[_pct_index(len(vals), 99.0)]
        else:
            out["p50"] = out["p99"] = None
        return out


class _Timer:
    """Context manager: observes elapsed seconds into a histogram and records
    a span on the registry's Chrome-trace timeline."""

    __slots__ = ("_reg", "_hist", "_name", "_t0")

    def __init__(self, reg, hist, name):
        self._reg = reg
        self._hist = hist
        self._name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        dt = time.perf_counter() - self._t0
        self._hist.observe(dt)
        self._reg.add_span(self._name, self._t0, dt)
        return False


class MetricsRegistry:
    """Process-wide metric store. Creation is locked; each metric carries its
    own lock, so hot-path updates never contend on the registry lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}
        self._spans = collections.deque(maxlen=_MAX_SPANS)
        self._span_lock = threading.Lock()
        # fleet tracing: trace-id-hex -> deque of spans, LRU-evicted so a
        # process pays a bounded footprint no matter how many traced
        # requests pass through (guarded by _span_lock)
        self._trace_spans = collections.OrderedDict()
        # LRU over LABELED series only: (kind, name, labelkey) -> store.
        # Unlabeled series are module-lifetime handles and never evict;
        # labeled ones (replica=..., op=...) churn with fleet membership
        # and must not grow without bound (guarded by _lock).
        self._labeled = collections.OrderedDict()
        self._series_evictions = Counter()
        self._counters[("metrics.series_evictions", ())] = \
            self._series_evictions
        # who this process is in the fleet (role + registry-lease id);
        # stamped by serve/router startup, exported with every trace pull
        self._node = {"role": None, "node_id": None}

    # ------------------------------------------------------------- identity

    def set_node_identity(self, role=None, node_id=None):
        """Record this process's fleet identity (role + replica/router id
        from its registry lease). Rides every TRACE_EXPORT / DEBUG_DUMP
        payload so the collector can label spans by process."""
        if role is not None:
            self._node["role"] = str(role)
        if node_id is not None:
            self._node["node_id"] = str(node_id)

    def node_identity(self) -> dict:
        return {"role": self._node["role"], "node_id": self._node["node_id"],
                "pid": os.getpid()}

    # -------------------------------------------------------------- creation

    def _get(self, store, kind, name, labels, factory):
        key = (name, _labelkey(labels))
        m = store.get(key)
        if m is None:
            with self._lock:
                m = store.get(key)
                if m is None:
                    m = store[key] = factory()
                    if key[1]:
                        self._labeled[(kind,) + key] = store
                        while len(self._labeled) > _MAX_LABELED_SERIES:
                            (_, n2, lk2), st2 = \
                                self._labeled.popitem(last=False)
                            st2.pop((n2, lk2), None)
                            self._series_evictions.inc()
        elif key[1]:
            # labeled hit: refresh recency so ACTIVE replicas' series
            # outlive departed ones (labeled access is request-rate at
            # worst, so the lock here never touches a step-loop hot path)
            with self._lock:
                lru_key = (kind,) + key
                if lru_key in self._labeled:
                    self._labeled.move_to_end(lru_key)
        return m

    def counter(self, name, **labels) -> Counter:
        return self._get(self._counters, "c", name, labels, Counter)

    def gauge(self, name, **labels) -> Gauge:
        return self._get(self._gauges, "g", name, labels, Gauge)

    def histogram(self, name, **labels) -> Histogram:
        return self._get(self._histograms, "h", name, labels, Histogram)

    def timer(self, name, **labels) -> _Timer:
        return _Timer(self, self.histogram(name, **labels),
                      _flatname(name, _labelkey(labels)))

    # ----------------------------------------------------------------- spans

    def add_span(self, name, t0_perf, dur_s, cat="host", args=None,
                 trace_id=None, parent=None, span_id=None):
        """Record one completed host-side range for Chrome-trace export.
        ``t0_perf`` is a time.perf_counter() value; timestamps are stored in
        microseconds relative to the process epoch. ``args`` (a small dict,
        e.g. ``{"request_id": "req-7"}``) lands on the Chrome-trace event's
        ``args`` field so Perfetto can group/filter spans by request.

        When ``trace_id`` (hex string) is given the span ALSO lands in that
        trace's bounded ring for the fleet collector (TRACE_EXPORT);
        ``parent``/``span_id`` are the upstream hop's span id and this
        process's own (hex). Untraced spans take the exact pre-fleet path —
        no ring lookup, no allocation beyond the one tuple."""
        entry = (name, cat, (t0_perf - _EPOCH) * 1e6,
                 dur_s * 1e6, threading.get_ident(), args)
        with self._span_lock:
            self._spans.append(entry)
            if trace_id is not None:
                ring = self._trace_spans.get(trace_id)
                if ring is None:
                    ring = self._trace_spans[trace_id] = \
                        collections.deque(maxlen=_MAX_TRACE_SPANS)
                    while len(self._trace_spans) > _MAX_TRACES:
                        self._trace_spans.popitem(last=False)
                else:
                    self._trace_spans.move_to_end(trace_id)
                ring.append(entry + (parent, span_id))

    def spans_for_trace(self, trace_id) -> list:
        """Chrome-trace events recorded under ``trace_id`` (hex string) by
        THIS process. Timestamps are unix-epoch microseconds (wall-rebased),
        so the fleet collector can merge exports from many processes onto
        one timeline without knowing their perf_counter origins."""
        with self._span_lock:
            ring = self._trace_spans.get(trace_id)
            spans = list(ring) if ring is not None else []
        events = []
        for name, cat, ts, dur, tid, args, parent, span_id in spans:
            a = dict(args) if args else {}
            a["trace_id"] = trace_id
            if parent is not None:
                a["parent"] = parent
            if span_id is not None:
                a["span"] = span_id
            events.append({"name": name, "cat": cat, "ph": "X",
                           "pid": os.getpid(), "tid": tid,
                           "ts": round(ts + _EPOCH_UNIX_US, 3),
                           "dur": round(dur, 3), "args": a})
        return events

    # --------------------------------------------------------------- exports

    def snapshot(self) -> dict:
        """Flat JSON-ready dict of everything the process has recorded."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        return {
            "counters": {_flatname(n, lk): c.value
                         for (n, lk), c in counters.items()},
            "gauges": {_flatname(n, lk): g.value
                       for (n, lk), g in gauges.items()},
            "histograms": {_flatname(n, lk): h.summary()
                           for (n, lk), h in hists.items()},
        }

    def to_json(self, indent=None) -> str:
        return json.dumps(self.snapshot(), indent=indent)

    def chrome_trace(self) -> dict:
        """Spans in Chrome ``traceEvents`` format plus the metric snapshot
        under the top-level ``metrics`` key (round-trips through
        `paddle.profiler.load_profiler_result`)."""
        with self._span_lock:
            spans = list(self._spans)
        events = []
        for name, cat, ts, dur, tid, args in spans:
            ev = {"name": name, "cat": cat, "ph": "X", "pid": os.getpid(),
                  "tid": tid, "ts": round(ts, 3), "dur": round(dur, 3)}
            if args:
                ev["args"] = dict(args)
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metrics": self.snapshot()}

    def to_prometheus(self) -> str:
        """Zero-dependency Prometheus text exposition (format 0.0.4) of
        every counter/gauge/histogram — histograms render as summaries
        (p50/p99 quantiles + _sum/_count). Standard scrapers consume this
        via the serve PROMETHEUS wire op or the stdlib http exporter
        (`observability/prometheus.py`)."""
        from paddle_tpu.observability.prometheus import render
        return render(self)

    def export_chrome_trace(self, path) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def reset(self):
        """Zero every metric IN PLACE and drop the spans (tests / bench rung
        isolation). Metrics are zeroed rather than dropped because the
        instrumented modules cache their handles at import time — dropping
        entries would orphan those handles and silently lose their counts."""
        with self._lock:
            stores = (list(self._counters.values()),
                      list(self._gauges.values()),
                      list(self._histograms.values()))
        for store in stores:
            for m in store:
                m.reset()
        with self._span_lock:
            self._spans.clear()
            self._trace_spans.clear()


# the process-wide default registry every instrumented layer reports to
metrics = MetricsRegistry()

# module-level conveniences bound to the default registry
counter = metrics.counter
gauge = metrics.gauge
histogram = metrics.histogram
timer = metrics.timer
snapshot = metrics.snapshot
reset = metrics.reset
chrome_trace = metrics.chrome_trace
export_chrome_trace = metrics.export_chrome_trace
to_prometheus = metrics.to_prometheus
set_node_identity = metrics.set_node_identity
node_identity = metrics.node_identity
spans_for_trace = metrics.spans_for_trace
