"""Prometheus text exposition for the metrics registry — zero dependency.

Two consumers:
- ``MetricsRegistry.to_prometheus()`` (delegates to :func:`render`): the
  exposition string, also served over the wire as the serve PROMETHEUS op
  (`inference/serve.py` op 6) so existing wire clients can scrape without
  HTTP;
- :func:`start_http_exporter`: an optional stdlib ``http.server`` endpoint
  (``GET /metrics``) so standard Prometheus scrapers work against any
  paddle_tpu process — serve, a training driver, a bench run — with no
  custom client at all (``python -m paddle_tpu.inference.serve
  --metrics-port P`` wires it up for the server).

Mapping (exposition format 0.0.4):
- counters/gauges keep their values; names sanitize ``.`` and any other
  non-``[a-zA-Z0-9_:]`` byte to ``_`` (``engine.steps`` ->
  ``engine_steps``);
- histograms render as **summaries**: ``{quantile="0.5"|"0.99"}`` sample
  lines from the bounded reservoir plus ``_sum``/``_count`` — the registry
  keeps a reservoir, not fixed buckets, so a summary is the honest
  translation (quantiles are over the last 512 observations).

Stdlib-only on purpose, like the rest of ``observability/``.
"""
from __future__ import annotations

import re
import threading

__all__ = ["render", "start_http_exporter"]

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _name(raw: str) -> str:
    n = _NAME_OK.sub("_", str(raw))
    if not n or n[0].isdigit():
        n = "_" + n
    return n


def _escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n") \
                 .replace('"', '\\"')


def _labels(labelkey, extra=()) -> str:
    pairs = [(_LABEL_OK.sub("_", str(k)), _escape(v))
             for k, v in tuple(labelkey) + tuple(extra)]
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"


def _value(v) -> str:
    v = float(v)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render(registry=None) -> str:
    """The full exposition document for ``registry`` (default: the
    process-wide one). Groups samples by metric name with one ``# TYPE``
    header per group, Prometheus's required layout."""
    if registry is None:
        from paddle_tpu.observability import metrics as registry
    with registry._lock:
        counters = dict(registry._counters)
        gauges = dict(registry._gauges)
        hists = dict(registry._histograms)

    by_name: dict = {}

    def _add(kind, name, line):
        by_name.setdefault((name, kind), []).append(line)

    for (raw, lk), c in sorted(counters.items()):
        n = _name(raw)
        _add("counter", n, f"{n}{_labels(lk)} {_value(c.value)}")
    for (raw, lk), g in sorted(gauges.items()):
        n = _name(raw)
        _add("gauge", n, f"{n}{_labels(lk)} {_value(g.value)}")
    for (raw, lk), h in sorted(hists.items()):
        n = _name(raw)
        s = h.summary()
        lines = []
        for q, key in ((0.5, "p50"), (0.99, "p99")):
            if s[key] is not None:
                lines.append(
                    f"{n}{_labels(lk, (('quantile', q),))} {_value(s[key])}")
        lines.append(f"{n}_sum{_labels(lk)} {_value(s['total'])}")
        lines.append(f"{n}_count{_labels(lk)} {_value(s['count'])}")
        for ln in lines:
            _add("summary", n, ln)

    out = []
    for (n, kind), lines in sorted(by_name.items()):
        out.append(f"# TYPE {n} {kind}")
        out.extend(lines)
    return "\n".join(out) + ("\n" if out else "")


def start_http_exporter(host="127.0.0.1", port=0, registry=None):
    """Serve ``GET /metrics`` (and ``/``) from a daemon thread; returns the
    live ``ThreadingHTTPServer`` (``.server_address[1]`` is the bound port,
    ``.shutdown()`` stops it). Scrape with any Prometheus server:

        scrape_configs:
          - job_name: paddle_tpu
            static_configs: [{targets: ["host:port"]}]
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path.split("?")[0].rstrip("/") not in ("", "/metrics"):
                self.send_error(404)
                return
            body = render(registry).encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # scrapes must not spam stderr
            pass

    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="pt-metrics-exporter")
    t.start()
    return srv
