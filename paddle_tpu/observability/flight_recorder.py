"""Flight recorder + watchdog: "why did the engine stop at 03:12?".

Aggregates and traces explain latency; a HANG explains nothing — the
process just stops answering. Two pieces close that gap:

- :class:`FlightRecorder` — a bounded, thread-safe ring of recent
  structured events (request admissions/retirements, step sequence
  numbers, compile starts, train steps). Cheap enough to leave on
  permanently; old events fall off, memory never grows. The process-wide
  default ring is ``flight`` (mirroring ``metrics``).
- :class:`Watchdog` — a daemon thread that polls a *progress* reading
  (e.g. the ``engine.steps`` counter). If the loop it guards is busy but
  progress has not advanced within the deadline, it dumps the event ring
  + the live per-request traces + the full metrics snapshot to a JSON
  file and notes the path on stderr — a post-mortem artifact instead of a
  silent hang. Exactly ONE dump per distinct stall: after dumping it
  re-arms only when progress advances again.

Wired into `inference/engine.py` (`DecodeEngine.start_watchdog`, on by
default under `serve_loop`) and `train/scan_step.py`
(`ScanTrainStep.start_watchdog`). Knobs: ``PADDLE_WATCHDOG_S`` (deadline
seconds, default 300; <= 0 disables the serve-loop watchdog) and
``PADDLE_WATCHDOG_DIR`` (dump directory, default the system temp dir).

Stdlib-only, like everything under ``observability/``.
"""
from __future__ import annotations

import collections
import json
import os
import sys
import tempfile
import threading
import time

from paddle_tpu.observability import metrics

__all__ = ["FlightRecorder", "Watchdog", "flight", "dump_ring"]

_EVENTS = 2048          # default ring capacity


class FlightRecorder:
    """Bounded ring of recent structured events."""

    def __init__(self, capacity: int = _EVENTS):
        self._ring = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, **fields):
        """Append one event. ``fields`` must be JSON-serializable scalars —
        the dump is a post-mortem artifact, keep entries small."""
        with self._lock:
            self._seq += 1
            self._ring.append({"seq": self._seq, "t": time.time(),
                               "kind": kind, **fields})

    def events(self) -> list:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


# the process-wide default ring every instrumented layer records into
flight = FlightRecorder()


def _default_dump_dir():
    return os.environ.get("PADDLE_WATCHDOG_DIR") or tempfile.gettempdir()


def dump_ring(label, out_dir=None, recorder=None, **extra) -> str:
    """Write the flight ring + the metrics snapshot (+ any ``extra``
    JSON-serializable context) to a post-mortem JSON file and return its
    path — the shared artifact writer behind the soak harness's
    first-failure dump and the liveness monitor's PeerLost dump
    (`distributed/liveness.py`); the watchdog keeps its own richer
    payload (per-request traces, stall metadata). ``PADDLE_WATCHDOG_DIR``
    picks the directory like the watchdog's."""
    out_dir = out_dir or _default_dump_dir()
    os.makedirs(out_dir, exist_ok=True)
    rec = recorder if recorder is not None else flight
    path = os.path.join(
        out_dir, f"{label}_{os.getpid()}_{int(time.time())}.json")
    with open(path, "w") as f:
        json.dump({"label": str(label), **extra,
                   "events": rec.events(),
                   "metrics": metrics.snapshot()}, f, indent=1)
    return path


def _slo_section(n_usage: int = 32) -> dict:
    """The stall dump's "what was the fleet promising, and to whom" block:
    currently-firing SLO alerts (every live evaluator), recent alert
    transitions, and the last N usage records. Lazy imports + a blanket
    guard: the dump writer must survive anything."""
    out = {"firing": [], "events": [], "usage": []}
    try:
        from paddle_tpu.observability.slo import active_alerts, recent_events
        out["firing"] = active_alerts()
        out["events"] = recent_events()
    except Exception:  # noqa: BLE001 — post-mortems must always land
        pass
    try:
        from paddle_tpu.observability.usage import usage_log
        out["usage"] = usage_log.last(n_usage)
    except Exception:  # noqa: BLE001
        pass
    return out


def default_deadline(fallback: float = 300.0) -> float:
    """Deadline seconds from ``PADDLE_WATCHDOG_S`` (<= 0 disables)."""
    try:
        return float(os.environ.get("PADDLE_WATCHDOG_S", fallback))
    except ValueError:
        return fallback


class Watchdog:
    """Stall detector for a step loop.

    name      : goes into the dump filename and payload
    progress  : () -> comparable — advances every loop iteration (a
                Counter.value read is the usual choice)
    busy      : () -> bool — True while the loop HAS work; no-progress
                while idle is not a stall (default: always busy)
    deadline_s: dump when busy and progress is frozen this long
    traces    : () -> list[RequestTrace] whose `to_dict()`s go in the dump
    recorder  : FlightRecorder to snapshot (default the process ring)
    interval_s: poll period (default deadline/4, floored at 10 ms)
    """

    def __init__(self, name, progress, *, busy=None, deadline_s=300.0,
                 dump_dir=None, traces=None, recorder=None, interval_s=None):
        self.name = str(name)
        self._progress = progress
        self._busy = busy or (lambda: True)
        self.deadline_s = float(deadline_s)
        self.dump_dir = dump_dir or _default_dump_dir()
        self._traces = traces or (lambda: [])
        self._recorder = recorder if recorder is not None else flight
        self._interval = max(0.01, interval_s if interval_s is not None
                             else self.deadline_s / 4.0)
        self._stop = threading.Event()
        self._thread = None
        self._armed_since = None     # first no-progress-while-busy sighting
        self._last_progress = None
        self._dumped_at = None       # progress value the last dump fired on
        self.dump_count = 0
        self.dump_paths: list[str] = []
        self._g_stalls = metrics.counter("watchdog.stalls", loop=self.name)

    # ---------------------------------------------------------------- thread

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"pt-watchdog-{self.name}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self._interval):
            try:
                self.check()
            except Exception as e:  # noqa: BLE001 — the guard must survive
                print(f"[watchdog:{self.name}] check failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)

    # ----------------------------------------------------------------- logic

    def check(self, now=None):
        """One poll (the thread calls this; tests can call it directly)."""
        now = time.perf_counter() if now is None else now
        p = self._progress()
        if p != self._last_progress or not self._busy():
            # moving, or legitimately idle: reset the stall clock and
            # re-arm the one-dump-per-stall latch once progress resumes
            self._last_progress = p
            self._armed_since = None
            if p != self._dumped_at:
                self._dumped_at = None
            return
        if self._armed_since is None:
            self._armed_since = now
            return
        stalled = now - self._armed_since
        if stalled >= self.deadline_s and self._dumped_at is None:
            # latch only AFTER the dump lands: a failed write (unwritable
            # dir, transient IO error) propagates to _run's guard and the
            # next poll retries — a hard hang must not end up artifact-less
            # because the first attempt failed
            self.dump(stalled_s=stalled, progress=p)
            self._dumped_at = p
            self._g_stalls.inc()

    def dump(self, stalled_s=None, progress=None) -> str:
        """Write the post-mortem JSON; returns its path."""
        os.makedirs(self.dump_dir, exist_ok=True)
        path = os.path.join(
            self.dump_dir,
            f"watchdog_{self.name}_{os.getpid()}_{int(time.time())}"
            f"_{self.dump_count}.json")
        payload = {
            "watchdog": self.name,
            "pid": os.getpid(),
            "wall_time": time.time(),
            "stalled_for_s": round(stalled_s, 3) if stalled_s is not None
            else None,
            "progress": progress,
            "deadline_s": self.deadline_s,
            "events": self._recorder.events(),
            "traces": [t.to_dict() for t in self._traces()],
            "metrics": metrics.snapshot(),
            "slo": _slo_section(),
        }
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
        self.dump_count += 1
        self.dump_paths.append(path)
        print(f"[watchdog:{self.name}] no progress for "
              f"{payload['stalled_for_s']}s — flight recorder dumped to "
              f"{path}", file=sys.stderr)
        return path
