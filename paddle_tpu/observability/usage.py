"""Per-request usage metering: what did ONE request actually cost?

Aggregate counters answer "how busy is the engine"; multi-tenant serving,
QoS scheduling and billing all need the per-request answer. Every
request that TERMINATES — tokens delivered, typed error, cancel,
deadline expiry, or a migration splice resolving the original future —
emits one usage record through :func:`emit_request`, called from the
single termination choke point (``GenerateRequest._finish`` in
`inference/engine.py`) on the FIRST completion only.

A record carries the token economy of the request:

- ``prompt_tokens`` — the submitted prompt length;
- ``prefill_computed`` — prompt tokens a prefill program actually ran
  over (chunk/tail tokens, mirroring ``engine.prefill_tokens``);
- ``prefill_saved`` — prompt tokens answered from cache instead
  (prefix-store hits + KV-tier re-uploads + warm-migration imports);
- ``generated`` / ``spec_accepted`` — tokens delivered, and how many of
  them speculation contributed beyond the 1/step baseline;
- ``kv_page_steps`` — KV pages held x decode steps held: the
  occupancy integral, the capacity a request charged the pool
  (computed analytically at slot detach — zero per-step work);
- queue wait / TTFT / e2e from the request's :class:`RequestTrace`;
- ``migrations`` and ``imported`` — how many times the request moved;
- ``tenant`` — reserved passthrough for the multi-tenant roadmap item.

Records land in a bounded in-memory ring (always on; termination-rate
cost only) and fold into cumulative ``usage.*`` counters that ride the
STATS payload, so the fleet plane rolls up fleet-wide spend with no new
wire op. :meth:`UsageLog.configure` additionally appends each record to
a size-rotated JSONL file — the billing/audit artifact. Unconfigured,
no file I/O ever happens and the decode step path is untouched.

Stdlib-only, like everything under ``observability/``.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

from paddle_tpu.observability import metrics

__all__ = ["UsageLog", "usage_log", "emit_request", "typed_error"]

_RING = 256            # records kept in memory (stall dumps, tests, smoke)


def typed_error(error):
    """The TYPE of a request's terminal error string — the ``'Cancelled:
    client went away'`` convention's head — or None for success."""
    if not error:
        return None
    head = str(error).split(":", 1)[0].strip()
    return head if head.replace("_", "").isalnum() else "Error"


class UsageLog:
    """Bounded ring + ``usage.*`` counters + optional rotating JSONL."""

    def __init__(self, capacity=_RING):
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=int(capacity))
        self._path = None
        self._max_bytes = 1 << 20
        self._keep = 3
        self._emitted = 0
        # handles cached once — emit() is termination-rate, but there is
        # no reason to pay registry lookups per record either
        self._m_requests = metrics.counter("usage.requests")
        self._m_errors = metrics.counter("usage.errors")
        self._m_prompt = metrics.counter("usage.prompt_tokens")
        self._m_computed = metrics.counter("usage.prefill_computed_tokens")
        self._m_saved = metrics.counter("usage.prefill_saved_tokens")
        self._m_generated = metrics.counter("usage.generated_tokens")
        self._m_spec = metrics.counter("usage.spec_accepted_tokens")
        self._m_page_steps = metrics.counter("usage.kv_page_steps")
        self._m_migrations = metrics.counter("usage.migrations")

    # ------------------------------------------------------------- configure

    def configure(self, path=None, max_bytes=1 << 20, keep=3):
        """Enable (path given) or disable (None) the JSONL file sink.
        When an append would push the file past ``max_bytes`` it rotates
        ``path -> path.1 -> ... -> path.<keep>`` (oldest dropped)."""
        with self._lock:
            self._path = os.fspath(path) if path else None
            self._max_bytes = int(max_bytes)
            self._keep = max(0, int(keep))

    # ----------------------------------------------------------------- emit

    def emit(self, rec):
        """Fold one record into the ring, the counters, and the file."""
        with self._lock:
            self._ring.append(rec)
            self._emitted += 1
            self._m_requests.inc()
            if rec.get("error"):
                self._m_errors.inc()
            self._m_prompt.inc(int(rec.get("prompt_tokens", 0) or 0))
            self._m_computed.inc(int(rec.get("prefill_computed", 0) or 0))
            self._m_saved.inc(int(rec.get("prefill_saved", 0) or 0))
            self._m_generated.inc(int(rec.get("generated", 0) or 0))
            self._m_spec.inc(int(rec.get("spec_accepted", 0) or 0))
            self._m_page_steps.inc(int(rec.get("kv_page_steps", 0) or 0))
            self._m_migrations.inc(int(rec.get("migrations", 0) or 0))
            path = self._path
            if path is None:
                return
            try:
                line = json.dumps(rec, default=str) + "\n"
                try:
                    size = os.path.getsize(path)
                except OSError:
                    size = 0
                if size and size + len(line) > self._max_bytes:
                    self._rotate(path)
                with open(path, "a") as f:
                    f.write(line)
            except Exception:  # noqa: BLE001 — metering must never kill serving
                pass

    def _rotate(self, path):
        if self._keep <= 0:
            os.replace(path, path + ".1")  # still bound the live file
            return
        for i in range(self._keep, 1, -1):
            src = f"{path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{path}.{i}")
        os.replace(path, f"{path}.1")

    # -------------------------------------------------------------- readback

    def last(self, n=1):
        with self._lock:
            recs = list(self._ring)
        return recs[-int(n):]

    def records(self):
        with self._lock:
            return list(self._ring)

    @property
    def emitted(self):
        return self._emitted

    def reset(self):
        """Drop the ring (tests / bench rung isolation; counters are the
        registry's to reset)."""
        with self._lock:
            self._ring.clear()
            self._emitted = 0


# the process-wide log every engine reports into
usage_log = UsageLog()


def emit_request(req, error=None, log=None):
    """Build + emit the UsageRecord for one terminated engine request.

    Reads the ``u_*`` accounting fields the engine mirrors onto each
    `GenerateRequest` alongside its aggregate counters, plus the
    request's `RequestTrace` timing marks. Called exactly once per
    request from ``GenerateRequest._finish``; never raises.
    """
    try:
        tr = getattr(req, "trace", None)
        t_accept = getattr(tr, "t_accept", None)
        t_submit = getattr(tr, "t_submit", None) or t_accept
        t_admit = getattr(tr, "t_admit", None)
        t_first = getattr(tr, "t_first_token", None)
        t_done = getattr(tr, "t_done", None)

        def _span(a, b):
            return round(b - a, 6) if a is not None and b is not None \
                else None

        prompt = getattr(req, "prompt", None)
        rec = {
            "t": time.time(),
            "request_id": getattr(tr, "request_id", None),
            "tenant": getattr(req, "tenant", None),
            "prompt_tokens": int(getattr(prompt, "size", 0) or 0),
            "prefill_computed": int(getattr(req, "u_prefill_computed", 0)),
            "prefill_saved": int(getattr(req, "u_prefill_saved", 0)),
            "generated": int(getattr(req, "u_generated", 0)),
            "spec_accepted": int(getattr(req, "u_spec_accepted", 0)),
            "kv_page_steps": int(getattr(req, "u_page_steps", 0)),
            "migrations": int(getattr(req, "u_migrations", 0)),
            "imported": bool(getattr(req, "imported", False)),
            "queue_wait_s": _span(t_submit, t_admit),
            "ttft_s": _span(t_accept, t_first),
            "e2e_s": _span(t_accept, t_done),
            "error": typed_error(error),
        }
        (log if log is not None else usage_log).emit(rec)
    except Exception:  # noqa: BLE001 — metering must never kill serving
        pass
