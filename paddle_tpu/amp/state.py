"""AMP autocast state + per-op cast decisions.

Ref: white/black lists at `python/paddle/fluid/dygraph/amp/auto_cast.py:44-105`
(incl. the BF16 lists at :104); cast decisions are inlined in generated forwards in
the reference (`eager/eager_amp_auto_cast.h`). On TPU the natural low precision is
bfloat16, which needs no loss scaling.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core import dtype as dtype_mod

# ops computed in low precision under O1 (ref WHITE_LIST)
WHITE_LIST = {"linear", "matmul", "bmm", "conv2d", "conv1d", "conv3d", "mv",
              "conv2d_transpose", "einsum", "mm"}
# ops kept in fp32 under O1 (ref BLACK_LIST — numerically sensitive)
BLACK_LIST = {"exp", "log", "square", "log_softmax", "softmax", "mean", "sum",
              "cross_entropy", "softmax_with_cross_entropy", "norm", "cumsum",
              "layer_norm", "batch_norm", "reduce_mean", "reduce_sum", "pow",
              "rsqrt", "sigmoid_cross_entropy_with_logits"}


class _AmpState:
    __slots__ = ("enabled", "level", "dtype", "custom_white", "custom_black")

    def __init__(self):
        self.enabled = False
        self.level = "O1"
        self.dtype = np.dtype(dtype_mod.bfloat16)
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state() -> _AmpState:
    return _state


def in_amp_context() -> bool:
    return _state.enabled


def amp_dtype():
    return _state.dtype


def amp_cast_inputs(op_name, *tensors):
    """Cast float inputs per autocast policy; identity when AMP is off."""
    if not _state.enabled:
        return tensors if len(tensors) > 1 else tensors[0]
    white = (WHITE_LIST | _state.custom_white) - _state.custom_black
    black = (BLACK_LIST | _state.custom_black) - _state.custom_white
    low = _state.dtype
    if _state.level == "O2":
        target = None if op_name in black else low
    else:
        if op_name in white:
            target = low
        elif op_name in black:
            target = np.dtype(np.float32)
        else:
            target = None
    if target is None:
        return tensors if len(tensors) > 1 else tensors[0]
    out = []
    for t in tensors:
        if t is not None and jnp.issubdtype(t.dtype, jnp.floating) \
                and t.dtype != target:
            out.append(t.astype(target))
        else:
            out.append(t)
    return tuple(out) if len(out) > 1 else out[0]
