"""paddle.amp — auto_cast + GradScaler.

Ref: `python/paddle/amp/auto_cast.py`, `amp/grad_scaler.py:26` over `AmpScaler`
(`fluid/dygraph/amp/loss_scaler.py:44`). On TPU the default AMP dtype is bfloat16
(same exponent range as fp32), so dynamic loss scaling is a no-op by default — the
GradScaler keeps the full found_inf/dynamic-scale contract for float16 use.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from paddle_tpu.amp.state import amp_state, WHITE_LIST, BLACK_LIST
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core import dtype as dtype_mod


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    st = amp_state()
    prev = (st.enabled, st.level, st.dtype, st.custom_white, st.custom_black)
    st.enabled = enable
    st.level = level
    st.dtype = np.dtype(dtype_mod.convert_dtype(dtype))
    st.custom_white = set(custom_white_list or ())
    st.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (st.enabled, st.level, st.dtype, st.custom_white, st.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the AMP dtype, keeping fp32 master
    weights in the optimizer (ref: `python/paddle/amp/auto_cast.py` amp_decorate)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        d = dtype_mod.convert_dtype(dtype)
        for m in model_list:
            for p in m.parameters():
                if jnp.issubdtype(p.dtype, jnp.floating):
                    p._master = Tensor(p._data, _internal=True)  # fp32 master copy
                    p._write(p._data.astype(d))
        if optimizers is not None:
            opt_list = [optimizers] if not isinstance(optimizers, (list, tuple)) \
                else list(optimizers)
            for opt in opt_list:
                opt._use_master_weights = True
    if optimizers is None:
        return models
    return models, optimizers


class _OptState:
    """Per-optimizer scaler state (ref `amp/grad_scaler.py` OptimizerState)."""
    INIT, UNSCALED, STEPPED = 0, 1, 2


class GradScaler:
    """Dynamic loss scaler (ref: `python/paddle/amp/grad_scaler.py:26`).

    Tracks per-optimizer INIT/UNSCALED/STEPPED state like the reference, so the
    documented ``unscale_(); clip; step(); update()`` pattern never
    double-unscales, and step-after-step raises instead of silently corrupting
    training. ``update()`` resets states and is left to the caller (``minimize``
    bundles step + update). Eager-only: found_inf concretizes the grads, so use
    bf16 autocast (no scaler) inside ``to_static`` steps."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False            # any optimizer overflowed this round
        self._optimizer_states: dict[int, int] = {}
        self._found_inf_per_opt: dict[int, bool] = {}

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        st = self._optimizer_states.get(id(optimizer), _OptState.INIT)
        if st == _OptState.UNSCALED:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer since "
                "the last update()")
        if st == _OptState.STEPPED:
            raise RuntimeError("unscale_() is being called after step()")
        params = optimizer._all_params()
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p.grad is not None:
                g = p.grad._data * inv
                p.grad._write(g)
                found = found or bool(jnp.any(~jnp.isfinite(g)))
        self._found_inf_per_opt[id(optimizer)] = found
        self._found_inf = self._found_inf or found
        self._optimizer_states[id(optimizer)] = _OptState.UNSCALED

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        st = self._optimizer_states.get(id(optimizer), _OptState.INIT)
        if st == _OptState.STEPPED:
            raise RuntimeError(
                "step() has already been called since the last update()")
        if st == _OptState.INIT:
            self.unscale_(optimizer)
        # skip decision is per optimizer: another optimizer's finite unscale
        # must not launder THIS optimizer's inf grads into a step
        if not self._found_inf_per_opt.get(id(optimizer), self._found_inf):
            optimizer.step()
        self._optimizer_states[id(optimizer)] = _OptState.STEPPED

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        if not self._enable:
            return
        if self._dynamic:
            if self._found_inf:
                self._bad_steps += 1
                self._good_steps = 0
                if self._bad_steps >= self._decr_every_n:
                    self._scale = max(self._scale * self._decr_ratio, 1.0)
                    self._bad_steps = 0
            else:
                self._good_steps += 1
                self._bad_steps = 0
                if self._good_steps >= self._incr_every_n_steps:
                    self._scale *= self._incr_ratio
                    self._good_steps = 0
        self._found_inf = False
        self._optimizer_states.clear()
        self._found_inf_per_opt.clear()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


AmpScaler = GradScaler
