"""Sparse 3-D convolution / pooling: gather → GEMM → scatter-add.

Counterpart of the reference's flagship sparse use —
`python/paddle/sparse/nn/layer/conv.py:135` (Conv3D), :270 (SubmConv3D) and
`paddle/phi/kernels/sparse/gpu/conv_kernel.cu` — redesigned for the MXU
(round-3 VERDICT missing #3): the CUDA kernel builds a per-kernel-offset
"rulebook" of (input site, output site) pairs on device; here the rulebook
is built host-side in numpy at call time (eager sparse patterns are
data-dependent by nature — same reason `coalesce` is host-driven), then the
compute is one dense [n_k, C_in] x [C_in, C_out] GEMM per kernel offset with
a scatter-add epilogue — gathers/GEMMs/scatters XLA maps straight onto the
TPU. Gradients to values AND weights fall out of the scatter/gather
transposes (the rulebook is static data inside the traced prim).

Layout follows the reference's sparse convention: x is an N-D sparse
`SparseCooTensor` of logical shape [N, D, H, W, C] with sparse_dim=4
(indices [4, nnz], values [nnz, C]); weights are [kd, kh, kw, C_in, C_out].
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def _out_spatial(sz, k, s, p, d):
    return (sz + 2 * p - d * (k - 1) - 1) // s + 1


def _build_rulebook(idx, spatial, ksize, stride, padding, dilation, subm):
    """Host-side rulebook: per kernel offset, the (input row, output row)
    pairs it connects, plus the output coordinate set.

    idx: [4, nnz] numpy (n, d, h, w). Returns (out_idx [4, n_out],
    pairs: list over kernel offsets of (in_rows, out_rows))."""
    coords = idx.T.astype(np.int64)                      # [nnz, 4]
    nnz = coords.shape[0]
    kd, kh, kw = ksize
    sd, sh, sw = stride
    pd, ph, pw = padding
    dd, dh, dw = dilation
    out_sp = tuple(_out_spatial(spatial[i], ksize[i], stride[i],
                                padding[i], dilation[i]) for i in range(3))
    offsets = [(a, b, c) for a in range(kd) for b in range(kh)
               for c in range(kw)]

    if subm:
        # submanifold: output sites == input sites (ref SubmConv3D :270)
        out_coords = coords
        key_of = {tuple(c): i for i, c in enumerate(map(tuple, coords))}
        pairs = []
        for (a, b, c) in offsets:
            # input site contributes to output at out = in - (k*dil - pad);
            # with the subm convention pad = dil*(k-1)//2 the kernel centers
            # on the site and the pattern is preserved
            od = coords[:, 1] + pd - a * dd
            oh = coords[:, 2] + ph - b * dh
            ow = coords[:, 3] + pw - c * dw
            in_rows, out_rows = [], []
            for r in range(nnz):
                key = (coords[r, 0], od[r], oh[r], ow[r])
                j = key_of.get(key)
                if j is not None:
                    in_rows.append(r)
                    out_rows.append(j)
            pairs.append((np.asarray(in_rows, np.int64),
                          np.asarray(out_rows, np.int64)))
        return out_coords.T, out_sp, pairs

    # standard conv: an input site feeds output o when
    # o*s = in + pad - k*dil  (divisible, in range)
    raw = {}
    hit_lists = []
    for (a, b, c) in offsets:
        num_d = coords[:, 1] + pd - a * dd
        num_h = coords[:, 2] + ph - b * dh
        num_w = coords[:, 3] + pw - c * dw
        ok = ((num_d % sd == 0) & (num_h % sh == 0) & (num_w % sw == 0))
        od, oh, ow = num_d // sd, num_h // sh, num_w // sw
        ok &= ((od >= 0) & (od < out_sp[0]) & (oh >= 0) & (oh < out_sp[1])
               & (ow >= 0) & (ow < out_sp[2]))
        rows = np.nonzero(ok)[0]
        keys = [(coords[r, 0], od[r], oh[r], ow[r]) for r in rows]
        for key in keys:
            raw.setdefault(key, len(raw))
        hit_lists.append((rows, keys))
    out_keys = sorted(raw.keys())
    key_of = {k: i for i, k in enumerate(out_keys)}
    pairs = []
    for rows, keys in hit_lists:
        out_rows = np.asarray([key_of[k] for k in keys], np.int64)
        pairs.append((rows.astype(np.int64), out_rows))
    out_coords = (np.asarray(out_keys, np.int64).reshape(-1, 4).T
                  if out_keys else np.zeros((4, 0), np.int64))
    return out_coords, out_sp, pairs


def _sparse_conv3d(x, weight, bias, stride, padding, dilation, subm):
    from paddle_tpu.sparse import SparseCooTensor

    ksize = tuple(int(s) for s in weight.shape[:3])
    stride, padding, dilation = (_triple(stride), _triple(padding),
                                 _triple(dilation))
    if subm:
        # DIVERGENCE from the reference (documented, r4 advisor): the
        # reference's ResetSubmKernelSizeAndStrides SILENTLY forces
        # stride=1, allows even kernels, and pads k/2 without accounting
        # for dilation (`phi/kernels/sparse/gpu/conv_kernel.cu` /
        # `sparse/nn/layer/conv.py:270`). Here stride!=1 and even kernels
        # RAISE (silent resets hide bugs; even kernels cannot center on
        # input sites), and padding is dilation-aware so dilated subm
        # convs actually preserve the sparsity pattern. Ported models that
        # relied on the silent reset must drop the stride argument.
        if stride != (1, 1, 1):
            raise ValueError(
                "SubmConv3D requires stride 1 (submanifold semantics; the "
                "reference silently RESETS stride to 1 — this build raises "
                "instead: pass stride=1 explicitly)")
        if any(k % 2 == 0 for k in ksize):
            raise ValueError(
                f"SubmConv3D requires ODD kernel sizes (got {ksize}): even "
                "kernels cannot center on the input sites, so the "
                "pattern-preserving contract has no consistent padding "
                "(the reference allows them with k/2 padding, shifting the "
                "receptive field half a voxel)")
        padding = tuple(dilation[i] * (ksize[i] - 1) // 2 for i in range(3))
    shape = x._dense_shape                     # [N, D, H, W, C]
    idx = np.asarray(x._indices._data)
    out_idx, out_sp, pairs = _build_rulebook(
        idx, shape[1:4], ksize, stride, padding, dilation, subm)
    n_out = out_idx.shape[1]
    c_out = int(weight.shape[-1])
    out_shape = (shape[0],) + out_sp + (c_out,)
    # pass the sparse tensor itself (its _data IS the values), so
    # .backward() accumulates into x.grad like the unary sparse ops
    w_t = ensure_tensor(weight)
    inputs = [x, w_t]
    if bias is not None:
        inputs.append(ensure_tensor(bias))
    pairs = [(jnp.asarray(i), jnp.asarray(o)) for i, o in pairs]

    def prim(vals, w, *b):
        wk = w.reshape((-1,) + tuple(w.shape[3:]))       # [K, Cin, Cout]
        out = jnp.zeros((n_out, c_out), vals.dtype)
        for k, (gi, go) in enumerate(pairs):
            if gi.shape[0] == 0:
                continue
            out = out.at[go].add(vals[gi] @ wk[k])
        if b:
            out = out + b[0]
        return out

    out_vals = apply(prim, *inputs, op_name="sparse_conv3d")
    return SparseCooTensor(Tensor(jnp.asarray(out_idx), _internal=True),
                           out_vals, out_shape,
                           stop_gradient=out_vals.stop_gradient)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """ref `paddle.sparse.nn.functional.conv3d`."""
    if groups != 1:
        raise NotImplementedError("sparse conv3d: groups > 1")
    return _sparse_conv3d(x, ensure_tensor(weight), bias, stride, padding,
                          dilation, subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """ref `paddle.sparse.nn.functional.subm_conv3d`."""
    if groups != 1:
        raise NotImplementedError("sparse subm_conv3d: groups > 1")
    return _sparse_conv3d(x, ensure_tensor(weight), bias, stride, padding,
                          dilation, subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0, data_format="NDHWC",
               name=None):
    """ref `paddle.sparse.nn.functional.max_pool3d`: max over the ACTIVE
    sites inside each window (inactive sites do not contribute zeros —
    the reference's sparse pooling semantics)."""
    from paddle_tpu.sparse import SparseCooTensor

    ksize = _triple(kernel_size)
    stride = _triple(stride) if stride is not None else ksize
    padding = _triple(padding)
    shape = x._dense_shape
    idx = np.asarray(x._indices._data)
    out_idx, out_sp, pairs = _build_rulebook(
        idx, shape[1:4], ksize, stride, padding, (1, 1, 1), subm=False)
    n_out = out_idx.shape[1]
    c = int(shape[-1])
    all_in = np.concatenate([i for i, _ in pairs]) if pairs else \
        np.zeros((0,), np.int64)
    all_out = np.concatenate([o for _, o in pairs]) if pairs else \
        np.zeros((0,), np.int64)
    gi = jnp.asarray(all_in)
    go = jnp.asarray(all_out)

    def prim(vals):
        return jax.ops.segment_max(vals[gi], go, num_segments=n_out)

    out_vals = apply(prim, x, op_name="sparse_max_pool3d")
    out_shape = (shape[0],) + out_sp + (c,)
    return SparseCooTensor(Tensor(jnp.asarray(out_idx), _internal=True),
                           out_vals, out_shape,
                           stop_gradient=out_vals.stop_gradient)


# ------------------------------------------------------------------ layers


from paddle_tpu.nn.layer import Layer as _Layer
from paddle_tpu.framework.param_attr import ParamAttr
from paddle_tpu.nn import initializer as I


class _Conv3DBase(_Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if data_format != "NDHWC":
            raise ValueError("sparse Conv3D supports NDHWC only (ref "
                             "conv.py sparse layout)")
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        ks = _triple(kernel_size)
        attr = ParamAttr._to_attr(weight_attr)
        if attr is None:
            attr = ParamAttr(initializer=I.XavierUniform())
        elif isinstance(attr, ParamAttr) and attr.initializer is None:
            attr.initializer = I.XavierUniform()
        self.weight = self.create_parameter(
            ks + (in_channels, out_channels), attr=attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)


class Conv3D(_Conv3DBase):
    """ref `python/paddle/sparse/nn/layer/conv.py:135`."""

    def forward(self, x):
        return conv3d(x, self.weight, self.bias, self._stride,
                      self._padding, self._dilation, self._groups)


class SubmConv3D(_Conv3DBase):
    """ref `python/paddle/sparse/nn/layer/conv.py:270`: output sites ==
    input sites, so deep sparse CNNs do not densify layer by layer."""

    def __init__(self, *args, key=None, **kwargs):
        super().__init__(*args, **kwargs)

    def forward(self, x):
        return subm_conv3d(x, self.weight, self.bias, self._stride,
                           self._padding, self._dilation, self._groups)


class MaxPool3D(_Layer):
    """ref `paddle.sparse.nn.MaxPool3D`."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._k = kernel_size
        self._s = stride
        self._p = padding

    def forward(self, x):
        return max_pool3d(x, self._k, self._s, self._p)
