"""paddle.sparse (ref: `python/paddle/sparse` over `phi/kernels/sparse/`).

COO/CSR tensors carried as (indices, values) with dense fallbacks through
jax.experimental.sparse (BCOO) where profitable; sparse NN layers land with the
sparse tower milestone.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


class SparseCooTensor(Tensor):
    """ref: `paddle/phi/core/sparse_coo_tensor.h`."""

    def __init__(self, indices, values, shape, stop_gradient=True):
        self._indices = ensure_tensor(indices)
        self._values = ensure_tensor(values)
        dense = jnp.zeros(tuple(int(s) for s in shape), self._values.dtype)
        idx = tuple(self._indices._data)
        dense = dense.at[idx].add(self._values._data)
        super().__init__(dense, stop_gradient=stop_gradient, _internal=True)
        self._dense_shape = tuple(int(s) for s in shape)

    def indices(self):
        if self._indices is None:
            self._materialize_sparse()
        return self._indices

    def _materialize_sparse(self):
        idx = jnp.stack(jnp.nonzero(self._data))
        self._indices = Tensor(idx, _internal=True)
        self._values = Tensor(self._data[tuple(idx)], _internal=True)

    def values(self):
        if self._values is None:
            self._materialize_sparse()
        return self._values

    def to_dense(self):
        t = Tensor(self._data, stop_gradient=self.stop_gradient,
                   _internal=True)
        t._grad_node = self._grad_node     # keep the autograd chain
        t._out_slot = self._out_slot
        return t

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = np.asarray(ensure_tensor(indices).numpy())
        vshape = tuple(np.asarray(ensure_tensor(values).numpy()).shape[1:])
        shape = tuple(int(m) + 1 for m in idx.max(axis=1)) + vshape
    return SparseCooTensor(indices, values, shape, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows_np = np.asarray(ensure_tensor(crows).numpy())
    cols_np = np.asarray(ensure_tensor(cols).numpy())
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return SparseCooTensor(indices, values, shape, stop_gradient)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


# ---------------------------------------------------------------- functional
# (ref `python/paddle/sparse/unary.py`, `binary.py`: the PHI sparse kernels
# compute on values; here COO/CSR carry a dense backing array so the dense XLA
# kernels serve directly, with results re-wrapped as sparse where meaningful)

def _rewrap(dense_out, like):
    """Wrap an op's dense result back as sparse WITHOUT severing the autograd
    chain: the result shares the dense Tensor's data and grad node; indices/
    values are recomputed lazily from the dense backing on access."""
    if not isinstance(like, SparseCooTensor):
        return dense_out
    t = SparseCooTensor.__new__(SparseCooTensor)
    Tensor.__init__(t, dense_out._data,
                    stop_gradient=dense_out.stop_gradient, _internal=True)
    t._grad_node = dense_out._grad_node
    t._out_slot = dense_out._out_slot
    t._indices = None              # lazy — see SparseCooTensor.indices()
    t._values = None
    t._dense_shape = tuple(dense_out.shape)
    return t


def add(x, y, name=None):
    import paddle_tpu as paddle
    return _rewrap(paddle.add(ensure_tensor(x), ensure_tensor(y)), x)


def subtract(x, y, name=None):
    import paddle_tpu as paddle
    return _rewrap(paddle.subtract(ensure_tensor(x), ensure_tensor(y)), x)


def multiply(x, y, name=None):
    import paddle_tpu as paddle
    return _rewrap(paddle.multiply(ensure_tensor(x), ensure_tensor(y)), x)


def divide(x, y, name=None):
    import paddle_tpu as paddle
    return _rewrap(paddle.divide(ensure_tensor(x), ensure_tensor(y)), x)


def matmul(x, y, name=None):
    """sparse @ dense -> dense (ref sparse matmul kernels)."""
    import paddle_tpu as paddle
    return paddle.matmul(ensure_tensor(x), ensure_tensor(y))


def masked_matmul(x, y, mask, name=None):
    """dense @ dense masked by a sparse pattern (ref masked_matmul)."""
    import paddle_tpu as paddle
    out = paddle.matmul(ensure_tensor(x), ensure_tensor(y))
    m = (mask.to_dense() if isinstance(mask, SparseCooTensor)
         else ensure_tensor(mask))
    return _rewrap(paddle.multiply(
        out, Tensor((m._data != 0).astype(out._data.dtype),
                    _internal=True)), mask)


def _unary(opname):
    def fn(x, name=None):
        import paddle_tpu as paddle
        return _rewrap(getattr(paddle, opname)(ensure_tensor(x)), x)
    fn.__name__ = opname
    return fn


sqrt = _unary("sqrt")
sin = _unary("sin")
tanh = _unary("tanh")
abs = _unary("abs")
neg = _unary("neg")
square = _unary("square")


def relu(x, name=None):
    import paddle_tpu.nn.functional as F
    return _rewrap(F.relu(ensure_tensor(x)), x)


import types as _types

nn = _types.SimpleNamespace()


class _ReLU:
    def __call__(self, x):
        return relu(x)


class _Softmax:
    def __init__(self, axis=-1):
        self.axis = axis

    def __call__(self, x):
        import paddle_tpu.nn.functional as F
        return _rewrap(F.softmax(ensure_tensor(x), axis=self.axis), x)


nn.ReLU = _ReLU
nn.Softmax = _Softmax
