"""paddle.sparse (ref: `python/paddle/sparse` over `phi/kernels/sparse/`).

COO/CSR tensors carried as (indices, values) with dense fallbacks through
jax.experimental.sparse (BCOO) where profitable; sparse NN layers land with the
sparse tower milestone.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.common import ensure_tensor


class SparseCooTensor(Tensor):
    """ref: `paddle/phi/core/sparse_coo_tensor.h`."""

    def __init__(self, indices, values, shape, stop_gradient=True):
        self._indices = ensure_tensor(indices)
        self._values = ensure_tensor(values)
        dense = jnp.zeros(tuple(int(s) for s in shape), self._values.dtype)
        idx = tuple(self._indices._data)
        dense = dense.at[idx].add(self._values._data)
        super().__init__(dense, stop_gradient=stop_gradient, _internal=True)
        self._dense_shape = tuple(int(s) for s in shape)

    def indices(self):
        return self._indices

    def values(self):
        return self._values

    def to_dense(self):
        return Tensor(self._data, _internal=True)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = np.asarray(ensure_tensor(indices).numpy())
        vshape = tuple(np.asarray(ensure_tensor(values).numpy()).shape[1:])
        shape = tuple(int(m) + 1 for m in idx.max(axis=1)) + vshape
    return SparseCooTensor(indices, values, shape, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows_np = np.asarray(ensure_tensor(crows).numpy())
    cols_np = np.asarray(ensure_tensor(cols).numpy())
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return SparseCooTensor(indices, values, shape, stop_gradient)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)
