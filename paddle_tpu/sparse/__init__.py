"""paddle.sparse (ref: `python/paddle/sparse/` over `phi/kernels/sparse/`).

TRUE sparse compute: a :class:`SparseCooTensor` carries ``indices [ndim,
nnz]`` and ``values [nnz, *dense_dims]`` and NO dense backing array — the
round-2 review flagged the old design as a dense-materialization shim. Ops
compute on the values with gather/scatter + segment forms (the XLA analog of
the reference's PHI sparse kernels):

- zero-preserving unary ops map over values only — O(nnz);
- ``multiply(coo, dense)`` gathers the dense operand at the nonzero sites —
  no [prod(shape)] intermediate;
- ``matmul(coo, dense)`` is a gather/scatter-add contraction — O(nnz * k)
  (ref `phi/kernels/sparse/matmul_kernel.h` csr x dense);
- ``masked_matmul`` computes ONLY the masked output sites via row/col
  gathers + per-site dot — O(nnz * k), never an [M, N] product;
- ``sparse.nn`` has ReLU / LeakyReLU / Softmax (per-row segment softmax) /
  BatchNorm (channel stats over the active sites, the sparse-BN semantics
  of ref `python/paddle/sparse/nn/layer/norm.py`).

Autograd rides the values: the tensor's ``_data`` IS the values array, so
``apply``-dispatched ops record on the normal tape and sparse grads come out
values-shaped (same sparsity pattern), matching the reference's sparse grad
convention. Ops with no sparse-efficient form fall back to ``to_dense()``
EXPLICITLY (add/subtract of mismatched patterns densify the result — stated,
not hidden).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor


class SparseCooTensor(Tensor):
    """ref: `paddle/phi/core/sparse_coo_tensor.h`. ``_data`` holds the
    values; dense ops that expect a dense array must go through
    ``to_dense()`` (the reference raises on dense-op-on-sparse too)."""

    def __init__(self, indices, values, shape, stop_gradient=True):
        ind = ensure_tensor(indices)
        val = ensure_tensor(values)
        super().__init__(val._data, stop_gradient=stop_gradient,
                         _internal=True)
        # keep the values' autograd chain: a sparse tensor built from an op
        # result must stay differentiable
        self._grad_node = val._grad_node
        self._out_slot = val._out_slot
        self._indices = Tensor(ind._data.astype(jnp.int64), _internal=True)
        self._dense_shape = tuple(int(s) for s in shape)

    # ------------------------------------------------------------- accessors

    @property
    def shape(self):
        return list(self._dense_shape)

    @property
    def ndim(self):
        return len(self._dense_shape)

    def nnz(self):
        return int(self._indices._data.shape[1])

    def indices(self):
        return self._indices

    def values(self):
        """Values view SHARING this tensor's data + grad chain."""
        t = Tensor(self._data, stop_gradient=self.stop_gradient,
                   _internal=True)
        t._grad_node = self._grad_node
        t._out_slot = self._out_slot
        return t

    def to_dense(self):
        """Differentiable scatter into the dense shape (d dense / d values
        is the gather at the nonzero sites)."""
        shape = self._dense_shape
        nsp = self._indices._data.shape[0]

        def prim(vals, idx):
            dense = jnp.zeros(shape, vals.dtype)
            return dense.at[tuple(idx[i] for i in range(nsp))].add(vals)

        return apply(prim, self, self._indices, op_name="sparse_to_dense")

    def numpy(self):
        return np.asarray(self.to_dense()._data)

    def coalesce(self):
        """Merge duplicate indices (eager: the merged nnz is data-dependent,
        ref `sparse/unary.py` coalesce)."""
        idx = np.asarray(self._indices._data)
        lin = np.ravel_multi_index(
            idx, self._dense_shape[: idx.shape[0]])
        uniq, inv = np.unique(lin, return_inverse=True)
        nsp = idx.shape[0]

        def prim(v):
            return jax.ops.segment_sum(v, jnp.asarray(inv),
                                       num_segments=len(uniq))

        new_vals = apply(prim, self, op_name="sparse_coalesce")
        new_idx = np.stack(np.unravel_index(
            uniq, self._dense_shape[:nsp]))
        return SparseCooTensor(Tensor(jnp.asarray(new_idx), _internal=True),
                               new_vals, self._dense_shape,
                               stop_gradient=self.stop_gradient)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = np.asarray(ensure_tensor(indices).numpy())
        vshape = tuple(np.asarray(ensure_tensor(values)._data).shape[1:])
        shape = tuple(int(m) + 1 for m in idx.max(axis=1)) + vshape
    return SparseCooTensor(indices, values, shape, stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """CSR enters as COO internally (row expansion); `is_sparse_csr` stays
    true on the result for API parity."""
    crows_np = np.asarray(ensure_tensor(crows)._data)
    cols_np = np.asarray(ensure_tensor(cols)._data)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    t = SparseCooTensor(Tensor(jnp.asarray(indices), _internal=True),
                        values, shape, stop_gradient)
    t._from_csr = True
    t.is_sparse_csr = lambda: True          # type: ignore[method-assign]
    return t


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def _same_pattern(x, y):
    a, b = x._indices._data, y._indices._data
    return a.shape == b.shape and bool(jnp.all(a == b))


# ---------------------------------------------------------------- functional
# (ref `python/paddle/sparse/unary.py`, `binary.py`)


def _values_unary(fn, x, name):
    """Zero-preserving elementwise op: values only, O(nnz). A dense input
    runs the SAME function on the dense array (params ride the closure)."""
    if not isinstance(x, SparseCooTensor):
        return apply(fn, ensure_tensor(x), op_name=name)
    out_vals = apply(fn, x, op_name=f"sparse_{name}")
    return SparseCooTensor(x._indices, out_vals, x._dense_shape,
                           stop_gradient=out_vals.stop_gradient)


def _unary(opname, jfn):
    def fn(x, name=None):
        return _values_unary(jfn, x, opname)
    fn.__name__ = opname
    return fn


sqrt = _unary("sqrt", jnp.sqrt)
sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
square = _unary("square", jnp.square)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)


def relu(x, name=None):
    return _values_unary(lambda v: jnp.maximum(v, 0), x, "relu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _values_unary(
        lambda v: jnp.where(v >= 0, v, negative_slope * v), x, "leaky_relu")


def relu6(x, name=None):
    return _values_unary(lambda v: jnp.clip(v, 0, 6), x, "relu6")


def pow(x, factor, name=None):
    return _values_unary(lambda v: jnp.power(v, factor), x, "pow")


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from paddle_tpu.core.dtype import convert_dtype
    out = _values_unary(
        (lambda v: v.astype(convert_dtype(value_dtype)))
        if value_dtype else (lambda v: v), x, "cast")
    if index_dtype is not None and isinstance(out, SparseCooTensor):
        out._indices = Tensor(out._indices._data.astype(
            convert_dtype(index_dtype)), _internal=True)
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    factor = scale
    if bias != 0.0:
        # a bias breaks zero-preservation — densify explicitly
        import paddle_tpu as paddle
        d = x.to_dense() if isinstance(x, SparseCooTensor) else x
        return paddle.scale(d, factor, bias, bias_after_scale)
    return _values_unary(lambda v: v * factor, x, "scale")


def add(x, y, name=None):
    """COO + COO: concatenated patterns (duplicates are legal COO; call
    .coalesce() to merge). Mixed sparse/dense densifies EXPLICITLY."""
    import paddle_tpu as paddle
    xs, ys = isinstance(x, SparseCooTensor), isinstance(y, SparseCooTensor)
    if xs and ys:
        if tuple(x._dense_shape) != tuple(y._dense_shape):
            raise ValueError("sparse add: shape mismatch "
                             f"{x._dense_shape} vs {y._dense_shape}")
        idx = jnp.concatenate([x._indices._data, y._indices._data], axis=1)
        vals = apply(lambda a, b: jnp.concatenate([a, b]), x, y,
                     op_name="sparse_add")
        return SparseCooTensor(Tensor(idx, _internal=True), vals,
                               x._dense_shape,
                               stop_gradient=vals.stop_gradient)
    if xs:
        return paddle.add(x.to_dense(), ensure_tensor(y))
    if ys:
        return paddle.add(ensure_tensor(x), y.to_dense())
    return paddle.add(ensure_tensor(x), ensure_tensor(y))


def subtract(x, y, name=None):
    if isinstance(y, SparseCooTensor):
        return add(x, neg(y), name)
    import paddle_tpu as paddle
    if isinstance(x, SparseCooTensor):
        return paddle.subtract(x.to_dense(), ensure_tensor(y))
    return paddle.subtract(ensure_tensor(x), ensure_tensor(y))


def multiply(x, y, name=None):
    """COO * dense gathers the dense operand at the nonzero sites (O(nnz));
    COO * COO multiplies values when the patterns match, else densifies
    explicitly (pattern intersection has data-dependent nnz)."""
    import paddle_tpu as paddle
    xs, ys = isinstance(x, SparseCooTensor), isinstance(y, SparseCooTensor)
    if xs and ys:
        if _same_pattern(x, y):
            vals = apply(lambda a, b: a * b, x, y, op_name="sparse_multiply")
            return SparseCooTensor(x._indices, vals, x._dense_shape,
                                   stop_gradient=vals.stop_gradient)
        return paddle.multiply(x.to_dense(), y.to_dense())
    if xs or ys:
        sp, dn = (x, y) if xs else (y, x)
        dn = ensure_tensor(dn)
        nsp = sp._indices._data.shape[0]

        def prim(vals, idx, da):
            picked = da[tuple(idx[i] for i in range(nsp))]
            return vals * picked

        vals = apply(prim, sp, sp._indices, dn, op_name="sparse_multiply")
        return SparseCooTensor(sp._indices, vals, sp._dense_shape,
                               stop_gradient=vals.stop_gradient)
    return paddle.multiply(ensure_tensor(x), ensure_tensor(y))


def divide(x, y, name=None):
    import paddle_tpu as paddle
    if isinstance(x, SparseCooTensor) and not isinstance(y, SparseCooTensor):
        dn = ensure_tensor(y)
        nsp = x._indices._data.shape[0]

        def prim(vals, idx, da):
            return vals / da[tuple(idx[i] for i in range(nsp))]

        vals = apply(prim, x, x._indices, dn, op_name="sparse_divide")
        return SparseCooTensor(x._indices, vals, x._dense_shape,
                               stop_gradient=vals.stop_gradient)
    a = x.to_dense() if isinstance(x, SparseCooTensor) else ensure_tensor(x)
    b = y.to_dense() if isinstance(y, SparseCooTensor) else ensure_tensor(y)
    return paddle.divide(a, b)


def matmul(x, y, name=None):
    """sparse [M, K] @ dense [K, N] -> dense [M, N] WITHOUT materializing a
    dense x: gather y's rows at the column indices, weight by the values and
    scatter-add into the output rows — O(nnz * N) (ref
    `phi/kernels/sparse/matmul_kernel.h`)."""
    import paddle_tpu as paddle
    if not isinstance(x, SparseCooTensor):
        y2 = y.to_dense() if isinstance(y, SparseCooTensor) else y
        return paddle.matmul(ensure_tensor(x), ensure_tensor(y2))
    if isinstance(y, SparseCooTensor):
        y = y.to_dense()
    if len(x._dense_shape) != 2:
        return paddle.matmul(x.to_dense(), ensure_tensor(y))
    m = x._dense_shape[0]
    dn = ensure_tensor(y)

    def prim(vals, idx, ya):
        rows, cols = idx[0], idx[1]
        contrib = vals[:, None] * ya[cols, :]          # [nnz, N]
        out = jnp.zeros((m, ya.shape[-1]), contrib.dtype)
        return out.at[rows].add(contrib)

    return apply(prim, x, x._indices, dn, op_name="sparse_matmul")


def masked_matmul(x, y, mask, name=None):
    """(dense [M,K] @ dense [K,N]) sampled ONLY at the mask's nonzero sites:
    per-site row/col gather + dot, O(nnz * K) — the [M, N] product never
    exists (ref `sparse/binary.py` masked_matmul / SDDMM)."""
    if not isinstance(mask, SparseCooTensor):
        import paddle_tpu as paddle
        out = paddle.matmul(ensure_tensor(x), ensure_tensor(y))
        m = ensure_tensor(mask)
        return paddle.multiply(out, Tensor(
            (m._data != 0).astype(out._data.dtype), _internal=True))
    xa = x.to_dense() if isinstance(x, SparseCooTensor) else ensure_tensor(x)
    ya = y.to_dense() if isinstance(y, SparseCooTensor) else ensure_tensor(y)

    def prim(xd, yd, idx):
        rows, cols = idx[0], idx[1]
        return jnp.sum(xd[rows, :] * yd[:, cols].T, axis=1)   # [nnz]

    vals = apply(prim, xa, ya, mask._indices, op_name="sparse_masked_matmul")
    return SparseCooTensor(mask._indices, vals, mask._dense_shape,
                           stop_gradient=vals.stop_gradient)


def _row_segment_softmax(x):
    """Per-row softmax over the NONZERO entries only (ref sparse softmax:
    zeros are treated as -inf, `phi/kernels/sparse/softmax_kernel.cc`)."""
    if len(x._dense_shape) != 2:
        raise ValueError("sparse softmax supports 2-D COO/CSR")
    m = x._dense_shape[0]

    def prim(vals, idx):
        rows = idx[0]
        row_max = jax.ops.segment_max(vals, rows, num_segments=m)
        e = jnp.exp(vals - row_max[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=m)
        return e / denom[rows]

    vals = apply(prim, x, x._indices, op_name="sparse_softmax")
    return SparseCooTensor(x._indices, vals, x._dense_shape,
                           stop_gradient=vals.stop_gradient)


def softmax(x, axis=-1, name=None):
    if isinstance(x, SparseCooTensor):
        if axis in (-1, 1) and len(x._dense_shape) == 2:
            return _row_segment_softmax(x)
        # densifying here would silently flip semantics (implicit zeros
        # would get exp(0) weight instead of -inf); the reference raises too
        raise ValueError(
            "sparse softmax supports only the last axis of a 2-D tensor "
            f"(got axis={axis}, ndim={len(x._dense_shape)})")
    import paddle_tpu.nn.functional as F
    return F.softmax(ensure_tensor(x), axis=axis)


# --------------------------------------------------------------------- nn
# ref `python/paddle/sparse/nn/` — layers over the functional forms above.

from paddle_tpu.nn.layer import Layer as _Layer


class ReLU(_Layer):
    def forward(self, x):
        return relu(x)


class LeakyReLU(_Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return leaky_relu(x, self.negative_slope)


class ReLU6(_Layer):
    def forward(self, x):
        return relu6(x)


class Softmax(_Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return softmax(x, axis=self.axis)


class BatchNorm(_Layer):
    """Sparse batch norm (ref `sparse/nn/layer/norm.py:BatchNorm`): channel
    statistics over the ACTIVE sites only — values are [nnz, C] for an
    ND-sparse tensor with a dense channel tail."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 name=None):
        super().__init__()
        if data_format not in ("NDHWC", "NHWC"):
            raise ValueError(
                "sparse BatchNorm is channel-last only (NDHWC/NHWC), got "
                f"{data_format!r} — values carry the channel tail")
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        from paddle_tpu.nn import initializer as I
        # weight_attr/bias_attr=False -> fixed scale/shift (dense norm.py
        # semantics); ParamAttr initializers/trainable are honored
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)
        import jax.numpy as _jnp
        self.register_buffer("_mean", Tensor(
            _jnp.zeros(num_features), _internal=True))
        self.register_buffer("_variance", Tensor(
            _jnp.ones(num_features), _internal=True))

    def forward(self, x):
        if not isinstance(x, SparseCooTensor):
            raise ValueError("sparse.nn.BatchNorm expects a SparseCooTensor")
        if x._data.ndim != 2 or x._data.shape[-1] != self.num_features:
            raise ValueError(
                f"values must be [nnz, {self.num_features}], got "
                f"{tuple(x._data.shape)}")
        mom = self.momentum
        eps = self.epsilon
        c = self.num_features
        w = self.weight if self.weight is not None else Tensor(
            jnp.ones(c), _internal=True)
        b = self.bias if self.bias is not None else Tensor(
            jnp.zeros(c), _internal=True)

        if self.training:
            def prim(vals, wa, ba):
                mu = vals.mean(axis=0)
                var = vals.var(axis=0)
                out = (vals - mu) / jnp.sqrt(var + eps) * wa + ba
                return out, mu, var

            out_vals, mu, var = apply(prim, x, w, b,
                                      op_name="sparse_batch_norm",
                                      n_outputs=3)
            self._mean._write(mom * self._mean._read()
                              + (1 - mom) * mu._data)
            self._variance._write(mom * self._variance._read()
                                  + (1 - mom) * var._data)
        else:
            def prim(vals, wa, ba, rm, rv):
                return (vals - rm) / jnp.sqrt(rv + eps) * wa + ba

            out_vals = apply(prim, x, w, b, self._mean,
                             self._variance, op_name="sparse_batch_norm")
        return SparseCooTensor(x._indices, out_vals, x._dense_shape,
                               stop_gradient=out_vals.stop_gradient)


class SyncBatchNorm(BatchNorm):
    """Under GSPMD the batch stats reduce across the mesh automatically when
    values are sharded — one class serves both (ref sparse SyncBatchNorm)."""


from paddle_tpu.sparse.conv import (  # noqa: E402
    Conv3D, SubmConv3D, MaxPool3D, conv3d, subm_conv3d, max_pool3d)

import types as _types

functional = _types.SimpleNamespace(
    conv3d=conv3d, subm_conv3d=subm_conv3d, max_pool3d=max_pool3d,
    relu=relu, softmax=softmax)

nn = _types.SimpleNamespace(
    ReLU=ReLU, LeakyReLU=LeakyReLU, ReLU6=ReLU6, Softmax=Softmax,
    BatchNorm=BatchNorm, SyncBatchNorm=SyncBatchNorm,
    Conv3D=Conv3D, SubmConv3D=SubmConv3D, MaxPool3D=MaxPool3D,
    functional=functional,
)
