"""Callbacks (ref: `python/paddle/hapi/callbacks.py` — ProgBarLogger,
ModelCheckpoint, EarlyStopping, LRScheduler)."""
from __future__ import annotations

import numbers
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda s, l=None: None)(step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            c.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            c.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_begin(mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epoch = 0
        self._start = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = []
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number) and k not in ("step",
                                                               "batch_size"):
                    items.append(f"{k}: {v:.4f}")
            print(f"Epoch {self.epoch} step {step} " + " ".join(items),
                  flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            items = [f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                     if isinstance(v, numbers.Number) and k not in (
                         "step", "batch_size")]
            print(f"Epoch {epoch} done in {dt:.1f}s " + " ".join(items),
                  flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and epoch % self.save_freq == 0:
            import os
            path = os.path.join(self.save_dir, str(epoch))
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir and self.model:
            import os
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.cmp = lambda cur, best: cur > best + self.min_delta
        else:
            self.cmp = lambda cur, best: cur < best - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            cur = (logs or {}).get(f"eval_{self.monitor}")
        if cur is None:
            return
        if self.best is None or self.cmp(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model is not None:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        from paddle_tpu.optimizer.lr import LRScheduler as Sched
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "metrics": metrics or []})
    return cl


class VisualDL(Callback):
    """Scalar-metrics logging callback (ref `hapi/callbacks.py:880`
    VisualDL). The reference writes VisualDL event files; this build keeps
    the callback contract (same tags ``train/<metric>`` per train step,
    ``eval/<metric>`` per epoch, rank-0-only writes) but logs to plain
    JSON-lines files under ``log_dir`` — readable by anything, no
    visualdl dependency. One line per scalar:
    ``{"tag": "train/loss", "step": 12, "value": 0.53}``.

    Also reads the process metrics registry (paddle_tpu.observability): at
    each epoch end the counters/gauges land as ``metrics/<name>`` scalars,
    so compile counts, cache hit rates, collective bytes and dataloader
    latency ride the same scalar stream as the losses."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self.epoch = 0
        self.train_step = 0
        self._fh = None
        self._last_registry_step = None

    def _is_write(self):
        from paddle_tpu.distributed import get_rank
        return get_rank() == 0

    def _writer(self):
        if self._fh is None:
            import os
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(os.path.join(self.log_dir, "scalars.jsonl"),
                            "a", buffering=1)
        return self._fh

    def _updates(self, logs, mode, step):
        if not self._is_write():
            return
        import json
        fh = self._writer()
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)) and v:
                v = v[0]
            if not isinstance(v, numbers.Number) or k in ("step",
                                                          "batch_size"):
                continue
            fh.write(json.dumps({"tag": f"{mode}/{k}", "step": int(step),
                                 "value": float(v)}) + "\n")

    def _emit_registry(self, step):
        """Registry counters + gauges as ``metrics/<name>`` scalar lines
        (histograms land as their mean) — rank-0-only like every write.
        At most once per step: the final epoch's emit and on_train_end land
        on the same step, and duplicating every line there would break
        consumers keying on unique (tag, step)."""
        if not self._is_write() or step == self._last_registry_step:
            return
        self._last_registry_step = step
        import json
        from paddle_tpu.observability import metrics
        snap = metrics.snapshot()
        fh = self._writer()
        for name, v in snap.get("counters", {}).items():
            fh.write(json.dumps({"tag": f"metrics/{name}", "step": int(step),
                                 "value": float(v)}) + "\n")
        for name, v in snap.get("gauges", {}).items():
            fh.write(json.dumps({"tag": f"metrics/{name}", "step": int(step),
                                 "value": float(v)}) + "\n")
        for name, h in snap.get("histograms", {}).items():
            if h.get("count"):
                fh.write(json.dumps(
                    {"tag": f"metrics/{name}.mean", "step": int(step),
                     "value": float(h["mean"])}) + "\n")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        self.train_step += 1
        self._updates(logs, "train", self.train_step)

    def on_epoch_end(self, epoch, logs=None):
        self._emit_registry(self.train_step)

    def on_eval_end(self, logs=None):
        self._updates(logs, "eval", self.epoch)

    def on_train_end(self, logs=None):
        self._emit_registry(self.train_step)
        if self._fh is not None:
            self._fh.close()
            self._fh = None
