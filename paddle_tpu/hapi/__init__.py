"""paddle.hapi (ref: `python/paddle/hapi/`)."""
from paddle_tpu.hapi.model import Model  # noqa: F401
from paddle_tpu.hapi import callbacks  # noqa: F401


def summary(net, input_size=None, dtypes=None, input=None):
    """Layer summary (ref: `python/paddle/hapi/model_summary.py`)."""
    import numpy as np
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=10) + 2
    lines = [f"{'Param':<{width}}{'Shape':<20}{'Count':>12}"]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<20}{n:>12,}")
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
