"""High-level Model API (ref: `python/paddle/hapi/model.py:1004` — Model.fit :1696,
train_batch :1145; the dygraph adapter :732 is the only execution path here)."""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.autograd import no_grad
from paddle_tpu.nn.layer import Layer
from paddle_tpu.io import DataLoader, Dataset


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._fused_step = None        # ScanTrainStep when the GPT route took
        self._fused_stale = False      # eager updates happened since capture

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]
        return self

    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            return outputs
        if isinstance(labels, (list, tuple)):
            return self._loss(outputs, *labels)
        return self._loss(outputs, labels)

    def train_batch(self, inputs, labels=None, update=True, loss_divisor=1):
        """One eager train step. ``update=False`` leaves the accumulated
        grads in place (gradient accumulation across calls); pass the
        accumulation count as ``loss_divisor`` so the effective gradient is
        the mean over the k batches, like one k-times-larger batch. The
        reported loss is always the UNdivided per-batch loss."""
        self._sync_fused()
        self._fused_stale = True       # eager update: fused state goes stale
        self.network.train()
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels)
        (loss / float(loss_divisor) if loss_divisor != 1 else loss).backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, labels))
            metrics.append(m.accumulate())
        return ([float(loss.numpy())], metrics) if metrics else \
            [float(loss.numpy())]

    # ---------------------------------------------- fused scanned GPT route

    def _network_computes_loss(self):
        """Networks whose forward(input, labels=...) returns (out, loss) —
        today the GPT causal-LM family — evaluate on their OWN loss when no
        loss fn was prepared."""
        try:
            from paddle_tpu.models.gpt import GPTForCausalLM
        except ImportError:
            return False
        return isinstance(self.network, GPTForCausalLM)

    def _maybe_fused_step(self, k):
        """A ScanTrainStep when (network, loss, optimizer) fit its envelope:
        a GPTForCausalLM whose OWN causal-LM loss is the objective (loss
        fn None), no streaming metrics (they need logits the fused step
        never materializes). k loader batches concatenate into one donated
        device program (scan over microbatches, single optimizer apply)."""
        if self._loss is not None or self._metrics or self._optimizer is None:
            return None
        try:
            from paddle_tpu.models.gpt import GPTForCausalLM
            from paddle_tpu.train import ScanTrainStep, ScanUnsupported
        except ImportError:
            return None
        if not isinstance(self.network, GPTForCausalLM):
            return None
        if self._fused_step is not None:
            if self._fused_step.microbatches != k:
                self._sync_fused()
                self._fused_step = None
            elif self._fused_stale:
                self._fused_step.refresh_from_model()
                self._fused_stale = False
        if self._fused_step is None:
            try:
                self._fused_step = ScanTrainStep(
                    self.network, self._optimizer, microbatches=k)
                self._fused_stale = False
            except ScanUnsupported:
                return None
        return self._fused_step

    def _sync_fused(self):
        if self._fused_step is not None and self._fused_step.dirty:
            self._fused_step.sync_to_model()

    def _fused_apply(self, fused, buf):
        """Run one fused step over the buffered loader batches. Equal
        batch sizes scan as microbatches; a ragged group (drop_last=False
        short final batch) runs as ONE microbatch — still a single
        optimizer apply over all its tokens."""
        arrs = [(np.asarray(ins[0].numpy() if isinstance(ins[0], Tensor)
                            else ins[0]),
                 np.asarray(lab.numpy() if isinstance(lab, Tensor)
                            else lab)) for ins, lab in buf]
        xs = np.concatenate([a for a, _ in arrs])
        ys = np.concatenate([b for _, b in arrs])
        sizes = {a.shape[0] for a, _ in arrs}
        m = len(buf) if len(sizes) == 1 else 1
        return fused.step(xs, ys, microbatches=m)

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self._sync_fused()
        self.network.eval()
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if self._loss is None and labels is not None and \
                self._network_computes_loss():
            lab = labels[0] if isinstance(labels, (list, tuple)) else labels
            if not isinstance(lab, Tensor):
                lab = Tensor(np.asarray(lab), _internal=True)
            outputs, loss = self.network(*inputs, labels=lab)
        else:
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        metrics = []
        for m in self._metrics:
            m.update(m.compute(outputs, labels))
            metrics.append(m.accumulate())
        return ([float(loss.numpy())], metrics) if metrics else \
            [float(loss.numpy())]

    @no_grad()
    def predict_batch(self, inputs):
        self._sync_fused()
        self.network.eval()
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        out = self.network(*inputs)
        return [out.numpy()] if isinstance(out, Tensor) else \
            [o.numpy() for o in out]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None,
            checkpoint_manager=None):
        """``checkpoint_manager``: a `paddle_tpu.train.CheckpointManager`
        makes the fit loop preemption-safe on the fused GPT route — it
        binds to the scanned step, resumes from LATEST (restoring params,
        optimizer state, rng, and the [epoch, batch] cursor; already-
        consumed batches of the resume epoch are skipped, which assumes a
        deterministic loader order — pass shuffle=False or a seeded
        sampler), checkpoints every ``manager.every`` optimizer steps, and
        on SIGTERM (`manager.install_sigterm()`) finishes the current
        accumulation group, writes a final synchronous checkpoint, and
        stops training cleanly. `TooManyBadSteps` from the bad-step ladder
        propagates to the caller with the state already rolled back."""
        from paddle_tpu.hapi.callbacks import config_callbacks
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=self._len_or_none(train_loader),
                                log_freq=log_freq, save_freq=save_freq,
                                save_dir=save_dir, verbose=verbose,
                                metrics=["loss"] + self._metric_names())
        k = max(1, int(accumulate_grad_batches or 1))
        fused = self._maybe_fused_step(k) if k >= 1 else None
        mgr = checkpoint_manager
        resume_epoch, resume_batch, mgr_cursor = 0, -1, None
        if mgr is not None:
            if fused is None:
                raise ValueError(
                    "checkpoint_manager needs the fused scanned GPT route "
                    "(GPTForCausalLM training on its own causal-LM loss "
                    "with a scan-fusable optimizer and no streaming "
                    "metrics) — the eager per-batch path has no "
                    "preemption-safe capture")
            if isinstance(train_data, Dataset) and shuffle:
                # resume skips batches BY LOADER INDEX: a reshuffled
                # restart would skip different samples than were trained,
                # silently double-training some and dropping others
                raise ValueError(
                    "checkpoint_manager resume replays the loader by "
                    "batch index — pass shuffle=False (or supply your own "
                    "deterministically-ordered DataLoader)")
            mgr.bind(fused)
            restored = mgr.restore()
            if restored is not None:
                cur = restored.get("data_cursor")
                if not (isinstance(cur, (list, tuple)) and len(cur) == 2):
                    # an int cursor (CheckpointManager.run) or a
                    # cursor-less manual save: fit cannot know which
                    # loader batches were consumed — resuming from epoch 0
                    # would silently double-train them
                    raise ValueError(
                        f"checkpoint at {restored['path']} has data_cursor="
                        f"{cur!r}; Model.fit resume needs the [epoch, "
                        "batch] cursor fit itself writes — resume this "
                        "checkpoint with CheckpointManager.run instead")
                resume_epoch, resume_batch = int(cur[0]), int(cur[1])
        cbks.on_begin("train")
        for epoch in range(epochs):
            if self.stop_training:
                break
            if mgr is not None and epoch < resume_epoch:
                continue          # fully consumed before the preemption
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            buf, pending, last_loss = [], 0, None
            consumed = -1          # last loader index actually trained on
            for step, batch in enumerate(train_loader):
                if num_iters is not None and step >= num_iters:
                    break
                if mgr is not None and epoch == resume_epoch \
                        and step <= resume_batch:
                    continue      # consumed before the preemption (the
                    # cursor lands on group boundaries, so no partial
                    # accumulation group is ever split across a resume)
                consumed = step
                cbks.on_batch_begin("train", step, logs)
                ins, labels = self._split_batch(batch)
                if fused is not None:
                    buf.append((ins, labels))
                    if len(buf) == k:
                        last_loss = self._fused_apply(fused, buf)
                        buf = []
                        if mgr is not None:
                            mgr_cursor = [epoch, step]
                            mgr.after_step(data_cursor=mgr_cursor)
                            if mgr.should_stop:
                                self.stop_training = True
                    # before the first apply there IS no loss yet: omit the
                    # key rather than poison callbacks with NaN
                    logs = (self._result_to_logs([last_loss], step,
                                                 batch_size)
                            if last_loss is not None
                            else {"step": step, "batch_size": batch_size})
                else:
                    update = k == 1 or (step + 1) % k == 0
                    pending = 0 if update else pending + 1
                    result = self.train_batch(ins, labels, update=update,
                                              loss_divisor=k)
                    logs = self._result_to_logs(result, step, batch_size)
                cbks.on_batch_end("train", step, logs)
                if self.stop_training:
                    break         # SIGTERM preemption: group boundary
                    # reached, buf is empty, final checkpoint below
            if fused is not None and buf:
                # leftover partial accumulation group at epoch end
                last_loss = self._fused_apply(fused, buf)
                logs["loss"] = last_loss
                if mgr is not None:
                    # the leftover apply is an optimizer step like any
                    # other: move the cursor past its batches and run the
                    # ladder/periodic save, or a later checkpoint would
                    # pair post-apply state with a pre-apply cursor and
                    # resume would double-apply these gradients. Cursor =
                    # last CONSUMED index — on a num_iters break `step`
                    # names a batch that never trained. The stop flag is
                    # honored here too: a loader whose epochs never fill a
                    # group only ever applies through THIS branch, and
                    # SIGTERM must not be deferred past it
                    mgr_cursor = [epoch, consumed]
                    mgr.after_step(data_cursor=mgr_cursor)
                    if mgr.should_stop:
                        self.stop_training = True
            elif pending:
                # flush generic-path leftover grads: they accumulated as
                # sum(g_i)/k over only `pending` batches — rescale to the
                # mean over the partial group (k/pending) so the final
                # update is not silently undersized
                scale = float(k) / float(pending)
                with no_grad():
                    for p in self._optimizer._parameter_list:
                        g = p.grad
                        if g is not None and hasattr(g, "_data"):
                            g._write(g._data * scale)
                self._optimizer.step()
                self._optimizer.clear_grad()
                pending = 0
            if fused is not None:
                self._sync_fused()   # state_dict/parameters see the epoch
            if eval_loader is not None and (epoch + 1) % eval_freq == 0 \
                    and not (mgr is not None and mgr.should_stop):
                # draining on SIGTERM: don't spend the eviction grace
                # window on eval — the final checkpoint below is the
                # contract, the eval can rerun after the resume
                eval_logs = self._run_eval(eval_loader, batch_size)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
        cbks.on_end("train", logs if "logs" in dir() else {})
        if mgr is not None:
            # drain any in-flight async write and leave a final complete
            # checkpoint — on the SIGTERM path this IS the graceful-drain
            # contract: rc 0 with the trained state durably on disk
            mgr.finalize(data_cursor=mgr_cursor)
        return self

    def _run_eval(self, eval_loader, batch_size):
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in eval_loader:
            ins, labels = self._split_batch(batch)
            result = self.eval_batch(ins, labels)
            loss = result[0] if isinstance(result, tuple) else result
            losses.append(loss[0])
        logs = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            name = m.name()
            acc = m.accumulate()
            if isinstance(name, list):
                for n, a in zip(name, acc if isinstance(acc, list) else [acc]):
                    logs[n] = a
            else:
                logs[name] = acc
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        if isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data
        return self._run_eval(eval_loader, batch_size)

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, predict=True)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    def _split_batch(self, batch, predict=False):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), batch[-1]
        return [batch], None

    def _metric_names(self):
        names = []
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _result_to_logs(self, result, step, batch_size):
        logs = {"step": step, "batch_size": batch_size}
        if isinstance(result, tuple):
            loss, metrics = result
            logs["loss"] = loss[0]
            for name, v in zip(self._metric_names(), metrics):
                logs[name] = v
        else:
            logs["loss"] = result[0]
        return logs

    def _len_or_none(self, loader):
        try:
            return len(loader)
        except Exception:
            return None

    def save(self, path, training=True):
        from paddle_tpu.framework import io as fio
        self._sync_fused()
        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os
        from paddle_tpu.framework import io as fio
        self.network.set_state_dict(fio.load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(fio.load(opt_path))
        if self._fused_step is not None:
            # re-pull the loaded state NOW (refresh also clears the dirty
            # flag — a later _sync_fused must not write pre-load weights
            # back over the checkpoint we just loaded)
            self._fused_step.refresh_from_model()
        self._fused_stale = False

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from paddle_tpu.hapi import summary as s
        return s(self.network, input_size, dtypes=dtype)
