"""paddle.autograd (ref: `python/paddle/autograd/__init__.py`): backward, grad,
PyLayer (ref `py_layer.py:558` EagerPyLayer), hooks."""
from __future__ import annotations

from paddle_tpu.core.autograd import (  # noqa: F401
    backward, grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled,
    GradNode, apply,
)
from paddle_tpu.core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *a):
        pass

    def mark_non_differentiable(self, *a):
        self._non_diff = a

    def set_materialize_grads(self, v):
        self.materialize_grads = v


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined autograd function (ref: ``paddle.autograd.PyLayer``).

    The subclass defines ``forward(ctx, *args)`` / ``backward(ctx, *grads)`` on
    Tensors. Implementation: run forward under no_grad, then register one tape node
    whose vjp calls the user's backward — the same shape as the reference's
    PyLayer GradNode (`paddle/fluid/eager/pylayer/py_layer_node.h`).
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        import jax.numpy as jnp
        from paddle_tpu.core import autograd as ag

        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with ag.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]

        record = ag.is_grad_enabled() and any(
            not t.stop_gradient and jnp.issubdtype(t.dtype, jnp.inexact)
            for t in tensor_inputs)
        if not record:
            return outputs

        def vjp_fn(cotangents):
            cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
            ct_tensors = [Tensor(c, stop_gradient=True, _internal=True)
                          for c in cts]
            with ag.no_grad():
                grads = cls.backward(ctx, *ct_tensors)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            out_grads = []
            gi = 0
            for a in args:
                if isinstance(a, Tensor):
                    g = grads[gi] if gi < len(grads) else None
                    gi += 1
                    out_grads.append(None if g is None else
                                     (g._data if isinstance(g, Tensor) else g))
            return tuple(out_grads)

        node = ag.GradNode(vjp_fn, tensor_inputs,
                           [(tuple(o.shape), o.dtype) for o in outs],
                           name=cls.__name__)
        import weakref
        for i, o in enumerate(outs):
            o.stop_gradient = False
            o._grad_node = node
            o._out_slot = i
            node.out_refs.append(weakref.ref(o))
        return outputs


EagerPyLayer = PyLayer


def hessian(func, xs, batch_axis=None):
    """Simple dense hessian via double jax.grad on the wrapped function."""
    import jax
    import jax.numpy as jnp
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)

    def wrapped(*arrs):
        ts = [Tensor(a, stop_gradient=False, _internal=True) for a in arrs]
        out = func(*ts) if len(ts) > 1 else func(ts[0])
        return out._data.reshape(())

    arrs = [t._data for t in xs_list]
    H = jax.hessian(wrapped, argnums=tuple(range(len(arrs))))(*arrs)
    if single:
        return Tensor(jnp.asarray(H[0][0]), _internal=True)
    return [[Tensor(jnp.asarray(h), _internal=True) for h in row] for row in H]


def jacobian(func, xs, batch_axis=None):
    import jax
    import jax.numpy as jnp
    single = isinstance(xs, Tensor)
    xs_list = [xs] if single else list(xs)

    def wrapped(*arrs):
        ts = [Tensor(a, stop_gradient=False, _internal=True) for a in arrs]
        out = func(*ts) if len(ts) > 1 else func(ts[0])
        return out._data

    arrs = [t._data for t in xs_list]
    J = jax.jacobian(wrapped, argnums=tuple(range(len(arrs))))(*arrs)
    if single:
        return Tensor(jnp.asarray(J[0]), _internal=True)
    return [Tensor(jnp.asarray(j), _internal=True) for j in J]
