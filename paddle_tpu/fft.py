"""Discrete Fourier transforms — ``paddle.fft`` surface.

TPU-native rebuild of the reference's fft tower (public API
``python/paddle/fft.py:175-1427``, C++ kernels ``paddle/phi/kernels/funcs/fft.h``
via pocketfft/cuFFT): here every transform lowers to ``jnp.fft`` so XLA emits the
FFT HLO directly; autograd rides the tape dispatcher like every other op.

Norm semantics match the reference (and numpy): "backward" (default), "ortho",
"forward". The helper ``fft_c2c/r2c/c2r`` internal names from the reference
collapse into the jnp calls.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

import jax

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core.autograd import apply
from paddle_tpu.ops.common import ensure_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")

# Some PJRT backends (e.g. the tunneled dev chip) have no FFT lowering; probe
# once and, when absent, pin the fft prims to the host CPU backend. Real
# TPU/XLA implements FFT natively, so the fast path is the default.
_FFT_ON_DEVICE = None


def _fft_on_device() -> bool:
    # Decide by platform, NOT by a probe execution: enqueueing an unsupported
    # op on a tunnel backend poisons its stream (subsequent d2h copies fail).
    # XLA's cpu/tpu/gpu backends all lower FFT; experimental tunnels may not.
    global _FFT_ON_DEVICE
    if _FFT_ON_DEVICE is None:
        try:
            from jax._src import xla_bridge
            names = set(xla_bridge.backends().keys())
        except Exception:
            names = set()
        _FFT_ON_DEVICE = jax.default_backend() in (
            "cpu", "gpu", "cuda", "rocm") or (
            jax.default_backend() == "tpu" and "axon" not in names)
    return _FFT_ON_DEVICE


def _apply_or_host(prim, *tensors, op_name):
    """Route through the autograd dispatcher when the backend lowers FFT;
    otherwise compute on the host CPU backend (forward-only — the probe only
    fails on dev-tunnel backends; real TPU/XLA lowers FFT natively).

    The host path round-trips through numpy because some tunnel backends also
    lack direct device<->device copies."""
    if _fft_on_device():
        return apply(prim, *tensors, op_name=op_name)
    cpu = jax.devices("cpu")[0]
    arrs = [np.asarray(t.numpy()) for t in tensors]
    with jax.default_device(cpu):
        out = prim(*[jnp.asarray(a) for a in arrs])
        if isinstance(out, (tuple, list)):
            host = [np.asarray(o) for o in out]
        else:
            host = np.asarray(out)

    def home(h):
        # complex arrays stay CPU-committed: backends without FFT typically
        # reject complex transfers too
        if np.issubdtype(h.dtype, np.complexfloating):
            return Tensor(jax.device_put(h, cpu), _internal=True)
        return Tensor(jnp.asarray(h), _internal=True)

    if isinstance(host, list):
        return tuple(home(h) for h in host)
    return home(host)


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be 'forward', 'backward' or 'ortho'"
        )
    return norm


def _wrap1(jfn, name):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        _check_norm(norm)
        x = ensure_tensor(x)
        return _apply_or_host(lambda a: jfn(a, n=n, axis=axis, norm=norm), x,
                              op_name=name)

    op.__name__ = name
    op.__doc__ = f"1-D ``{name}`` (paddle.fft.{name}; ref python/paddle/fft.py)."
    return op


def _wrapn(jfn, name):
    def op(x, s=None, axes=None, norm="backward", name=None):
        _check_norm(norm)
        x = ensure_tensor(x)
        return _apply_or_host(lambda a: jfn(a, s=s, axes=axes, norm=norm), x,
                              op_name=name)

    op.__name__ = name
    op.__doc__ = f"N-D ``{name}`` (paddle.fft.{name}; ref python/paddle/fft.py)."
    return op


def _wrap2(jfn, name):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        _check_norm(norm)
        x = ensure_tensor(x)
        if axes is not None and len(axes) != 2:
            raise ValueError(f"{name} expects exactly 2 axes, got {axes}")
        return _apply_or_host(lambda a: jfn(a, s=s, axes=axes, norm=norm), x,
                              op_name=name)

    op.__name__ = name
    op.__doc__ = f"2-D ``{name}`` (paddle.fft.{name}; ref python/paddle/fft.py:877-1243)."
    return op


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")

fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")

fft2 = _wrap2(jnp.fft.fftn, "fft2")
ifft2 = _wrap2(jnp.fft.ifftn, "ifft2")
rfft2 = _wrap2(jnp.fft.rfftn, "rfft2")
irfft2 = _wrap2(jnp.fft.irfftn, "irfft2")


def _hfftn(a, s=None, axes=None, norm="backward"):
    # hfftn = irfftn of the conjugate with "inverse" normalization flipped;
    # numpy has no hfftn — compose it the way the reference's fftn_c2r does
    # (python/paddle/fft.py:781).
    if axes is None:
        axes = tuple(range(a.ndim))
    inv = {"backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]
    return jnp.fft.irfftn(jnp.conj(a), s=s, axes=axes, norm=inv)


def _ihfftn(a, s=None, axes=None, norm="backward"):
    if axes is None:
        axes = tuple(range(a.ndim))
    inv = {"backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]
    return jnp.conj(jnp.fft.rfftn(a, s=s, axes=axes, norm=inv))


hfftn = _wrapn(_hfftn, "hfftn")
ihfftn = _wrapn(_ihfftn, "ihfftn")
hfft2 = _wrap2(_hfftn, "hfft2")
ihfft2 = _wrap2(_ihfftn, "ihfft2")


def fftfreq(n, d=1.0, dtype=None, name=None):
    """Sample frequencies for ``fft`` output bins (paddle.fft.fftfreq)."""
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        out = out.astype(dtype)
    return Tensor(out, _internal=True)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    """Sample frequencies for ``rfft`` output bins (paddle.fft.rfftfreq)."""
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        out = out.astype(dtype)
    return Tensor(out, _internal=True)


def fftshift(x, axes=None, name=None):
    """Shift the zero-frequency component to the center (paddle.fft.fftshift)."""
    x = ensure_tensor(x)
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x, op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    """Inverse of ``fftshift`` (paddle.fft.ifftshift)."""
    x = ensure_tensor(x)
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x, op_name="ifftshift")
