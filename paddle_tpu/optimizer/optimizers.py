"""Concrete optimizers (ref: `python/paddle/optimizer/{sgd,momentum,adam,adamw,...}.py`;
fused-kernel analogs of `_C_ops.adam_` at `adam.py:376`, `_C_ops.adamw_` at
`adamw.py:496`). Each update body is a pure jax fn jitted once and reused."""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.optimizer.optimizer import Optimizer


@jax.jit
def _sgd_update(p, g, lr, wd):
    g = g + wd * p
    return p - lr * g.astype(p.dtype)


class SGD(Optimizer):
    _FUSABLE = True

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        src = self._update_src(p)
        self._commit(p, src, _sgd_update(
            src._read(), grad._read().astype(src.dtype),
            jnp.asarray(lr, src.dtype), jnp.asarray(weight_decay, src.dtype)))

    def _fused_update(self, p32, g32, states, lr, wd, t):
        return p32 - lr * (g32 + wd * p32), []

    def _append_sparse_op(self, p, grad, lr, weight_decay, t=None):
        # row-scatter SGD (ref phi/kernels/selected_rows/sgd_kernel)
        src = self._update_src(p)
        w = src._read()
        rows = grad.rows
        vals = grad.values.astype(w.dtype)
        upd = vals + weight_decay * w[rows] if weight_decay else vals
        self._commit(p, src, w.at[rows].add(
            (-jnp.asarray(lr, w.dtype)) * upd))


@partial(jax.jit, static_argnames=("use_nesterov",))
def _momentum_update(p, g, velocity, lr, mu, wd, use_nesterov):
    g = (g + wd * p).astype(p.dtype)
    v = mu * velocity + g
    if use_nesterov:
        new_p = p - (g + mu * v) * lr
    else:
        new_p = p - lr * v
    return new_p, v


class Momentum(Optimizer):
    _FUSABLE = True

    def _fused_state_names(self):
        return ["velocity"]

    def _fused_update(self, p32, g32, states, lr, wd, t):
        g = g32 + wd * p32
        v = self._momentum * states[0] + g
        if self._use_nesterov:
            new_p = p32 - (g + self._momentum * v) * lr
        else:
            new_p = p32 - lr * v
        return new_p, [v]

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        src = self._update_src(p)
        vel = self._accumulator("velocity", p, dtype=src.dtype)
        new_p, new_v = _momentum_update(
            src._read(), grad._read().astype(src.dtype), vel._read(),
            jnp.asarray(lr, src.dtype), jnp.asarray(self._momentum, src.dtype),
            jnp.asarray(weight_decay, src.dtype), self._use_nesterov)
        self._commit(p, src, new_p)
        vel._write(new_v)


@partial(jax.jit, static_argnames=("decouple", "amsgrad"))
def _adam_update(p, g, m, v, vhat, lr, beta1, beta2, eps, t, wd, decouple=False,
                 amsgrad=False):
    g32 = g.astype(m.dtype)
    p32 = p.astype(m.dtype)
    if not decouple:
        g32 = g32 + wd * p32
    m = beta1 * m + (1 - beta1) * g32
    v = beta2 * v + (1 - beta2) * g32 * g32
    mhat = m / (1 - beta1 ** t)
    vv = v / (1 - beta2 ** t)
    if amsgrad:
        vhat = jnp.maximum(vhat, vv)
        denom = jnp.sqrt(vhat) + eps
    else:
        denom = jnp.sqrt(vv) + eps
    upd = mhat / denom
    if decouple:
        upd = upd + wd * p32
    new_p = (p32 - lr * upd).astype(p.dtype)
    return new_p, m, v, vhat


class Adam(Optimizer):
    _decoupled = False
    _FUSABLE = True

    def _fused_state_names(self):
        return (["moment1", "moment2", "moment2_max"] if self._amsgrad
                else ["moment1", "moment2"])

    def _fused_update(self, p32, g32, states, lr, wd, t):
        new_p, m, v, vhat = _adam_update(
            p32, g32, states[0], states[1],
            states[2] if self._amsgrad else jnp.zeros((), jnp.float32),
            lr, jnp.asarray(self._beta1, jnp.float32),
            jnp.asarray(self._beta2, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32), t,
            wd, decouple=self._decoupled, amsgrad=self._amsgrad)
        return new_p, ([m, v, vhat] if self._amsgrad else [m, v])

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def _append_sparse_op(self, p, grad, lr, weight_decay, t=None):
        # lazy-mode row-wise Adam (ref `phi/kernels/selected_rows/adam_kernel`,
        # `python/paddle/optimizer/adam.py` lazy_mode): moments and weights of
        # untouched rows are left alone
        m = self._accumulator("moment1", p, dtype=jnp.float32)
        v = self._accumulator("moment2", p, dtype=jnp.float32)
        src = self._update_src(p)
        w = src._read()
        rows = grad.rows
        g = grad.values.astype(jnp.float32)
        t_arr = (t if t is not None
                 else jnp.asarray(self._global_step, jnp.float32))
        b1 = jnp.asarray(self._beta1, jnp.float32)
        b2 = jnp.asarray(self._beta2, jnp.float32)
        w_rows = w[rows].astype(jnp.float32)
        if weight_decay and not self._decoupled:
            g = g + weight_decay * w_rows
        m_new = b1 * m._read()[rows] + (1 - b1) * g
        v_new = b2 * v._read()[rows] + (1 - b2) * g * g
        if self._amsgrad:
            vhat_acc = self._accumulator("moment2_max", p, dtype=jnp.float32)
            v_eff = jnp.maximum(vhat_acc._read()[rows], v_new)
            vhat_acc._write(vhat_acc._read().at[rows].set(v_eff))
        else:
            v_eff = v_new
        mhat = m_new / (1 - b1 ** t_arr)
        vhat = v_eff / (1 - b2 ** t_arr)
        new_rows = w_rows - lr * (mhat / (jnp.sqrt(vhat) + self._epsilon))
        if weight_decay and self._decoupled:
            new_rows = new_rows - lr * weight_decay * w_rows
        m._write(m._read().at[rows].set(m_new))
        v._write(v._read().at[rows].set(v_new))
        self._commit(p, src, w.at[rows].set(new_rows.astype(w.dtype)))

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        m = self._accumulator("moment1", p, dtype=jnp.float32)
        v = self._accumulator("moment2", p, dtype=jnp.float32)
        if self._amsgrad:
            vhat = self._accumulator("moment2_max", p, dtype=jnp.float32)
            vhat_in = vhat._read()
        else:
            vhat = None
            vhat_in = jnp.zeros((), jnp.float32)  # unused under static amsgrad=False
        t_arr = t if t is not None else jnp.asarray(self._global_step,
                                                   jnp.float32)
        src = self._update_src(p)
        new_p, new_m, new_v, new_vhat = _adam_update(
            src._read(), grad._read(), m._read(), v._read(), vhat_in,
            jnp.asarray(lr, jnp.float32), jnp.asarray(self._beta1, jnp.float32),
            jnp.asarray(self._beta2, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(t_arr, jnp.float32),
            jnp.asarray(weight_decay, jnp.float32),
            decouple=self._decoupled, amsgrad=self._amsgrad)
        self._commit(p, src, new_p)
        m._write(new_m)
        v._write(new_v)
        if self._amsgrad:
            vhat._write(new_vhat)


class AdamW(Adam):
    """Decoupled weight decay (ref `python/paddle/optimizer/adamw.py`)."""

    _decoupled = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _lr_wd_of(self, p, lr_arr):
        # per-param decay-mask / lr-ratio feed both the per-param path (step()
        # resolves lr/wd through here) and the fused per-element multipliers
        lr, wd = super()._lr_wd_of(p, lr_arr)
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            wd = 0.0
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        return lr, wd


@jax.jit
def _adagrad_update(p, g, moment, lr, eps, wd):
    g32 = g.astype(moment.dtype)
    p32 = p.astype(moment.dtype)
    g32 = g32 + wd * p32
    moment = moment + g32 * g32
    new_p = (p32 - lr * g32 / (jnp.sqrt(moment) + eps)).astype(p.dtype)
    return new_p, moment


class Adagrad(Optimizer):
    # elementwise update: rides the fused eager path AND the scanned donated
    # train step (paddle_tpu/train) via the same pure kernel
    _FUSABLE = True

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _fused_state_names(self):
        return ["moment"]

    def _functional_state_init(self, name, shape):
        if name == "moment" and self._init_acc:
            return jnp.full(shape, self._init_acc, jnp.float32)
        return jnp.zeros(shape, jnp.float32)

    def _fused_update(self, p32, g32, states, lr, wd, t):
        g = g32 + wd * p32
        moment = states[0] + g * g
        return p32 - lr * g / (jnp.sqrt(moment) + self._epsilon), [moment]

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        mom = self._accumulator(
            "moment", p, init=jnp.full(p._data.shape, self._init_acc, jnp.float32))
        src = self._update_src(p)
        new_p, new_m = _adagrad_update(
            src._read(), grad._read(), mom._read(), jnp.asarray(lr, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(weight_decay, jnp.float32))
        self._commit(p, src, new_p)
        mom._write(new_m)


@jax.jit
def _adamax_update(p, g, m, inf_norm, lr, beta1, beta2, eps, t, wd):
    g32 = g.astype(m.dtype)
    p32 = p.astype(m.dtype)
    g32 = g32 + wd * p32
    m = beta1 * m + (1 - beta1) * g32
    inf_norm = jnp.maximum(beta2 * inf_norm, jnp.abs(g32))
    new_p = (p32 - (lr / (1 - beta1 ** t)) * m / (inf_norm + eps)).astype(p.dtype)
    return new_p, m, inf_norm


class Adamax(Optimizer):
    _FUSABLE = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _fused_state_names(self):
        return ["moment", "inf_norm"]

    def _fused_update(self, p32, g32, states, lr, wd, t):
        g = g32 + wd * p32
        m = self._beta1 * states[0] + (1 - self._beta1) * g
        inf = jnp.maximum(self._beta2 * states[1], jnp.abs(g))
        new_p = p32 - (lr / (1 - self._beta1 ** t)) * m / (inf + self._epsilon)
        return new_p, [m, inf]

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        m = self._accumulator("moment", p, dtype=jnp.float32)
        inf = self._accumulator("inf_norm", p, dtype=jnp.float32)
        src = self._update_src(p)
        new_p, new_m, new_inf = _adamax_update(
            src._read(), grad._read(), m._read(), inf._read(),
            jnp.asarray(lr, jnp.float32), jnp.asarray(self._beta1, jnp.float32),
            jnp.asarray(self._beta2, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(t if t is not None else self._global_step, jnp.float32),
            jnp.asarray(weight_decay, jnp.float32))
        self._commit(p, src, new_p)
        m._write(new_m)
        inf._write(new_inf)


@jax.jit
def _adadelta_update(p, g, avg_sq, avg_upd, rho, eps, lr, wd):
    g32 = g.astype(avg_sq.dtype)
    p32 = p.astype(avg_sq.dtype)
    g32 = g32 + wd * p32
    avg_sq = rho * avg_sq + (1 - rho) * g32 * g32
    upd = jnp.sqrt(avg_upd + eps) / jnp.sqrt(avg_sq + eps) * g32
    avg_upd = rho * avg_upd + (1 - rho) * upd * upd
    return (p32 - lr * upd).astype(p.dtype), avg_sq, avg_upd


class Adadelta(Optimizer):
    _FUSABLE = True

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _fused_state_names(self):
        return ["avg_squared_grad", "avg_squared_update"]

    def _fused_update(self, p32, g32, states, lr, wd, t):
        g = g32 + wd * p32
        avg_sq = self._rho * states[0] + (1 - self._rho) * g * g
        upd = jnp.sqrt(states[1] + self._epsilon) / \
            jnp.sqrt(avg_sq + self._epsilon) * g
        avg_upd = self._rho * states[1] + (1 - self._rho) * upd * upd
        return p32 - lr * upd, [avg_sq, avg_upd]

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        sq = self._accumulator("avg_squared_grad", p, dtype=jnp.float32)
        up = self._accumulator("avg_squared_update", p, dtype=jnp.float32)
        src = self._update_src(p)
        new_p, new_sq, new_up = _adadelta_update(
            src._read(), grad._read(), sq._read(), up._read(),
            jnp.asarray(self._rho, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(lr, jnp.float32), jnp.asarray(weight_decay, jnp.float32))
        self._commit(p, src, new_p)
        sq._write(new_sq)
        up._write(new_up)


@partial(jax.jit, static_argnames=("centered",))
def _rmsprop_update(p, g, mean_sq, mom, mean_g, lr, rho, eps, momentum, wd,
                    centered=False):
    g32 = g.astype(mean_sq.dtype)
    p32 = p.astype(mean_sq.dtype)
    g32 = g32 + wd * p32
    mean_sq = rho * mean_sq + (1 - rho) * g32 * g32
    if centered:
        mean_g = rho * mean_g + (1 - rho) * g32
        denom = jnp.sqrt(mean_sq - mean_g * mean_g + eps)
    else:
        denom = jnp.sqrt(mean_sq + eps)
    mom = momentum * mom + lr * g32 / denom
    return (p32 - mom).astype(p.dtype), mean_sq, mom, mean_g


class RMSProp(Optimizer):
    _FUSABLE = True

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _fused_state_names(self):
        return ["mean_square", "momentum", "mean_grad"]

    def _fused_update(self, p32, g32, states, lr, wd, t):
        new_p, msq, mom, mg = _rmsprop_update(
            p32, g32, states[0], states[1], states[2], lr,
            jnp.asarray(self._rho, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(self._momentum, jnp.float32),
            wd, centered=self._centered)
        return new_p, [msq, mom, mg]

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        msq = self._accumulator("mean_square", p, dtype=jnp.float32)
        mom = self._accumulator("momentum", p, dtype=jnp.float32)
        mg = self._accumulator("mean_grad", p, dtype=jnp.float32)
        src = self._update_src(p)
        new_p, new_msq, new_mom, new_mg = _rmsprop_update(
            src._read(), grad._read(), msq._read(), mom._read(), mg._read(),
            jnp.asarray(lr, jnp.float32), jnp.asarray(self._rho, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(self._momentum, jnp.float32),
            jnp.asarray(weight_decay, jnp.float32), centered=self._centered)
        self._commit(p, src, new_p)
        msq._write(new_msq)
        mom._write(new_mom)
        mg._write(new_mg)


@jax.jit
def _lamb_update(p, g, m, v, lr, beta1, beta2, eps, t, wd):
    g32 = g.astype(m.dtype)
    p32 = p.astype(m.dtype)
    m = beta1 * m + (1 - beta1) * g32
    v = beta2 * v + (1 - beta2) * g32 * g32
    mhat = m / (1 - beta1 ** t)
    vhat = v / (1 - beta2 ** t)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p32
    w_norm = jnp.linalg.norm(p32)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return (p32 - lr * trust * r).astype(p.dtype), m, v


class Lamb(Optimizer):
    """LAMB (ref `python/paddle/optimizer/lamb.py`; dist variant
    `meta_optimizers/lamb_optimizer.py`)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        if self._exclude_fn is not None and self._exclude_fn(p):
            weight_decay = 0.0
        m = self._accumulator("moment1", p, dtype=jnp.float32)
        v = self._accumulator("moment2", p, dtype=jnp.float32)
        src = self._update_src(p)
        new_p, new_m, new_v = _lamb_update(
            src._read(), grad._read(), m._read(), v._read(), jnp.asarray(lr, jnp.float32),
            jnp.asarray(self._beta1, jnp.float32),
            jnp.asarray(self._beta2, jnp.float32),
            jnp.asarray(self._epsilon, jnp.float32),
            jnp.asarray(t if t is not None else self._global_step, jnp.float32),
            jnp.asarray(weight_decay, jnp.float32))
        self._commit(p, src, new_p)
        m._write(new_m)
        v._write(new_v)


class LarsMomentum(Momentum):
    # LARS needs a per-param trust ratio (norm(p)/norm(g)); the flat fused
    # update would silently degrade it to plain Momentum
    _FUSABLE = False
    """LARS (ref `meta_optimizers/lars_optimizer.py`, op `lars_momentum_op`)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=lars_weight_decay, grad_clip=grad_clip)
        self._lars_coeff = lars_coeff
        self._lars_epsilon = epsilon

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        w_norm = jnp.linalg.norm(p._read().astype(jnp.float32))
        g_norm = jnp.linalg.norm(grad._read().astype(jnp.float32))
        scaled = lr * self._lars_coeff * w_norm / (
            g_norm + weight_decay * w_norm + self._lars_epsilon)
        local_lr = jnp.where((w_norm > 0) & (g_norm > 0), scaled,
                             jnp.asarray(lr, jnp.float32))
        super()._append_optimize_op(p, grad, local_lr, weight_decay, t)
