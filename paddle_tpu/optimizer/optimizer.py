"""Optimizer base (ref: `python/paddle/optimizer/optimizer.py:98`).

The per-param update is one fused jitted jax function over (param, grad, state)
arrays — the analog of the reference's fused CUDA optimizer kernels
(`phi/kernels/gpu/adam_kernel.cu` etc.), supplied here by XLA fusion. All updates run
under no_grad and rebind param storage in place, so the same optimizer object works
eagerly and inside a captured train step.
"""
from __future__ import annotations

import collections

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, Parameter
from paddle_tpu.core.autograd import no_grad
from paddle_tpu.nn.clip import ClipGradBase
from paddle_tpu.optimizer.lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode (pass model.parameters())")
        self._parameter_list = list(parameters)
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for group in self._param_groups:
                flat.extend(group["params"])
            self._parameter_list = flat
        self._learning_rate = learning_rate
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            self._weight_decay = float(weight_decay)
        elif weight_decay is None:
            self._weight_decay = 0.0
        else:
            # regularizer object (paddle.regularizer.L1Decay/L2Decay) with a
            # coeff attribute; L1 is applied as sign(p)*coeff on the grad in
            # step(), L2 rides the fused update's weight_decay term
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay, "coeff", 0.0)))
            if getattr(weight_decay, "_kind", "l2") == "l1":
                self._l1_decay = self._weight_decay
                self._weight_decay = 0.0
        self._grad_clip = grad_clip
        if not hasattr(self, "_l1_decay"):
            self._l1_decay = 0.0
        self._accumulators: dict[str, dict[int, Tensor]] = collections.defaultdict(
            dict)
        self._fused_parts: dict = {}    # per-group flat state (see _fused_meta)
        self._global_step = 0
        self._use_master_weights = False
        self._master_weights: dict[int, Tensor] = {}
        self._name = name or type(self).__name__
        # lr lives in a Tensor so captured train steps thread it as state: the
        # scheduler updates it *outside* the compiled program (analog of the
        # reference feeding lr as a Variable into optimizer ops)
        self._lr_tensor = Tensor(jnp.asarray(self.get_lr(), jnp.float32),
                                 _internal=True)
        self._lr_tensor.persistable = True
        # step count as state too (adam bias correction inside captured steps)
        self._step_tensor = Tensor(jnp.asarray(0, jnp.int64), _internal=True)
        self._step_tensor.persistable = True
        if isinstance(self._learning_rate, LRScheduler):
            self._learning_rate._bind_optimizer(self)

    # ------------------------------------------------------------------ lr

    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when the lr is an LRScheduler; call "
                               "scheduler.step() instead")
        self._learning_rate = float(value)
        self._sync_lr_tensor(self._learning_rate)

    def _sync_lr_tensor(self, value):
        from paddle_tpu.core import tensor as tensor_mod
        if not tensor_mod.in_capture():
            self._lr_tensor._write(jnp.asarray(float(value), jnp.float32))

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler
        scheduler._bind_optimizer(self)

    # ------------------------------------------------------------------ state

    def _all_params(self):
        return self._parameter_list

    def _accumulator(self, name, p, init=None, dtype=None):
        store = self._accumulators[name]
        key = id(p)
        if key not in store:
            # ensure_compile_time_eval: lazy state creation may run inside the
            # abstract capture probe (static_function phase 1); the initial
            # value must be a concrete array, not a tracer, to survive the trace
            with jax.ensure_compile_time_eval():
                d = dtype or (jnp.float32 if self._use_master_weights
                              else p.dtype)
                arr = jnp.zeros(p._data.shape, d) if init is None else init
                t = Tensor(jnp.asarray(arr), _internal=True)
            t.persistable = True
            store[key] = t
        return store[key]

    def _master(self, p):
        key = id(p)
        if key not in self._master_weights:
            # amp.decorate(level="O2") stashes the pre-cast fp32 copy on the
            # param; prefer it so the master doesn't inherit bf16 rounding
            src = getattr(p, "_master", None)
            with jax.ensure_compile_time_eval():
                arr = src._data if src is not None else p._data
                mt = Tensor(arr.astype(jnp.float32), _internal=True)
            mt.persistable = True
            self._master_weights[key] = mt
        return self._master_weights[key]

    def _update_src(self, p):
        """The tensor the update math runs on: the param itself, or its fp32
        master copy under O2 multi-precision (ref adamw multi_precision path) —
        low-precision params otherwise round away small updates in the
        per-step down-cast."""
        if self._use_master_weights and p._data.dtype != jnp.float32:
            return self._master(p)
        return p

    def _commit(self, p, src, new_arr):
        """Write the updated value back: master keeps fp32, param gets the
        down-cast copy."""
        src._write(new_arr)
        if src is not p:
            p._write(new_arr.astype(p._data.dtype))

    # ------------------------------------------------------------------ step

    def _param_group_of(self, p):
        if self._param_groups is None:
            return None
        for g in self._param_groups:
            if any(q is p for q in g["params"]):
                return g
        return None

    def _lr_wd_of(self, p, lr_arr):
        group = self._param_group_of(p)
        lr = lr_arr
        wd = self._weight_decay
        if group is not None:
            lr = lr * float(group.get("learning_rate", 1.0))
            gwd = group.get("weight_decay", wd)
            wd = float(gwd) if gwd is not None else wd
        if hasattr(p, "optimize_attr"):
            lr = lr * float(getattr(p, "optimize_attr", {}).get(
                "learning_rate", 1.0))
        return lr, wd

    @no_grad()
    def step(self):
        from paddle_tpu.core import tensor as tensor_mod
        from paddle_tpu.core.selected_rows import SelectedRows
        from paddle_tpu.framework.flags import flag_value
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        # SelectedRows grads (sparse embedding) take the row-wise update path;
        # they bypass grad_clip like the reference's sparse grads do under
        # ClipGradByNorm (merge+clip would densify, defeating the point)
        if self._l1_decay:
            c = self._l1_decay

            def _l1(p, g):
                if isinstance(g, SelectedRows):
                    rows_sign = jnp.sign(p._data[g.rows]).astype(g.values.dtype)
                    return SelectedRows(g.rows, g.values + c * rows_sign,
                                        g.height)
                return tensor_mod.Tensor(
                    g._data + c * jnp.sign(p._data).astype(g._data.dtype),
                    _internal=True)

            params_grads = [(p, _l1(p, g)) for p, g in params_grads]
        sparse_pg = [(p, g) for p, g in params_grads
                     if isinstance(g, SelectedRows)]
        params_grads = [(p, g) for p, g in params_grads
                        if not isinstance(g, SelectedRows)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._global_step += 1
        if not tensor_mod.in_capture():
            # sync python-side lr/step into the state tensors; inside a captured
            # step these writes would bake constants, so they happen out-of-graph
            self._lr_tensor._write(jnp.asarray(self.get_lr(), jnp.float32))
            self._step_tensor._write(jnp.asarray(self._global_step, jnp.int64))
        else:
            self._step_tensor._write(self._step_tensor._read() + 1)
        lr_arr = self._lr_tensor._read()
        t_arr = self._step_tensor._read().astype(jnp.float32)
        for p, g in sparse_pg:
            lr, wd = self._lr_wd_of(p, lr_arr)
            self._append_sparse_op(p, g.merge(), lr, wd, t_arr)
        if self._FUSABLE and flag_value("tpu_fused_optimizer"):
            self._fused_step(params_grads, lr_arr, t_arr)
            return
        for p, g in params_grads:
            if g is None:
                continue
            lr, wd = self._lr_wd_of(p, lr_arr)
            self._append_optimize_op(p, g, lr, wd, t_arr)

    def _append_optimize_op(self, p, grad, lr, weight_decay, t=None):
        raise NotImplementedError

    def _append_sparse_op(self, p, grad, lr, weight_decay, t=None):
        """Row-wise update for a merged SelectedRows grad. Default: densify
        (correct, loses the sparsity win); SGD/Adam override with true
        row-scatter updates (ref `phi/kernels/selected_rows/` sgd/adam)."""
        from paddle_tpu.core.tensor import Tensor
        self._append_optimize_op(
            p, Tensor(grad.to_dense(), _internal=True), lr, weight_decay, t)

    # ---------------------------------------------------------- fused updates
    # Multi-tensor path: all parameters of a (src-dtype, param-dtype) group are
    # updated in ONE fused elementwise op over concatenated flat buffers — the
    # analog of the reference's fused adam/adamw CUDA kernels (`_C_ops.adam_`)
    # plus its coalesce_grad_tensor_pass. Per-param updates otherwise become
    # ~150 tiny sequential XLA fusions (~18ms/step on GPT-2-small on v5e).
    # Optimizer state (moments etc.) lives in flat per-group buffers; state_dict
    # slices per-param views out for checkpoint compatibility.

    _FUSABLE = False                    # subclasses with _fused_update opt in

    def _fused_state_names(self):
        return []

    def _functional_state_init(self, name, shape):
        """Initial value for a fused/functional state leaf that has no
        per-param accumulator to seed from (zeros for every stock optimizer
        except Adagrad's initial_accumulator_value)."""
        return jnp.zeros(shape, jnp.float32)

    def _fused_update(self, p32, g32, states, lr, wd, t):
        """states: list of flat f32 arrays (same order as _fused_state_names).
        Returns (new_p32, new_states)."""
        raise NotImplementedError

    # params at or above this size get individual updates: one big fusion per
    # tensor is already efficient and donation-aliased in-place; concatenating
    # them would add O(model) copy traffic. Small params (LN scales, biases)
    # drown in per-op overhead (~150 sequential tiny fusions), so they batch.
    _FUSE_MAX_NUMEL = 1 << 20

    def _fused_partition(self, params_grads):
        groups, singles = {}, []
        import numpy as np
        for p, g in params_grads:
            if g is None:
                continue
            if int(np.prod(p._data.shape) or 1) >= self._FUSE_MAX_NUMEL:
                singles.append((p, g))
                continue
            src = self._update_src(p)
            key = (str(src._data.dtype), str(p._data.dtype))
            groups.setdefault(key, []).append((p, g, src))
        return groups, singles

    def _fused_meta(self, key, pgs, lr_arr):
        """Build (once per partition) the per-group metadata: slice offsets,
        per-element lr-multiplier / weight-decay (scalars when uniform), and
        flat state tensors seeded from any per-param accumulators."""
        ids = tuple(id(p) for p, _, _ in pgs)
        meta = self._fused_parts.get(key)
        if meta is not None and meta["ids"] == ids:
            return meta
        if meta is not None:
            # param set changed (freeze/unfreeze): spill the old flat state
            # back to per-param accumulators so the rebuild reseeds from it
            # instead of silently restarting moments at zero
            self._fused_spill(key)
        import numpy as np
        sizes = [int(np.prod(p._data.shape)) or 1 for p, _, _ in pgs]
        offs = np.cumsum([0] + sizes)
        lrs, wds = [], []
        for p, _, _ in pgs:
            lr_m, wd = self._lr_wd_of(p, 1.0)
            lrs.append(float(lr_m))
            wds.append(float(wd))
        uniform_lr = len(set(lrs)) == 1
        uniform_wd = len(set(wds)) == 1
        with jax.ensure_compile_time_eval():
            if uniform_lr:
                lr_mul = jnp.asarray(lrs[0], jnp.float32)
            else:
                lr_mul = jnp.concatenate([
                    jnp.full((n,), s, jnp.float32)
                    for n, s in zip(sizes, lrs)])
            if uniform_wd:
                wd_vec = jnp.asarray(wds[0], jnp.float32)
            else:
                wd_vec = jnp.concatenate([
                    jnp.full((n,), s, jnp.float32)
                    for n, s in zip(sizes, wds)])
            states = []
            for name in self._fused_state_names():
                store = self._accumulators[name]
                chunks = []
                for (p, _, _), n in zip(pgs, sizes):
                    acc = store.pop(id(p), None)
                    chunks.append(acc._data.reshape(-1).astype(jnp.float32)
                                  if acc is not None
                                  else self._functional_state_init(name, (n,)))
                t = Tensor(jnp.concatenate(chunks), _internal=True)
                t.persistable = True
                states.append(t)
        meta = {"ids": ids, "sizes": sizes, "offs": offs, "lr_mul": lr_mul,
                "wd": wd_vec, "states": states}
        self._fused_parts[key] = meta
        return meta

    def _fused_step(self, params_grads, lr_arr, t_arr):
        groups, singles = self._fused_partition(params_grads)
        for p, g in singles:
            lr, wd = self._lr_wd_of(p, lr_arr)
            self._append_optimize_op(p, g, lr, wd, t_arr)
        for key, pgs in groups.items():
            meta = self._fused_meta(key, pgs, lr_arr)
            flat_g = jnp.concatenate(
                [g._read().reshape(-1).astype(jnp.float32) for _, g, _ in pgs])
            flat_p = jnp.concatenate(
                [s._read().reshape(-1) for _, _, s in pgs]).astype(jnp.float32)
            new_p, new_states = self._fused_update(
                flat_p, flat_g, [s._read() for s in meta["states"]],
                lr_arr * meta["lr_mul"], meta["wd"], t_arr)
            for st, arr in zip(meta["states"], new_states):
                st._write(arr)
            offs = meta["offs"]
            for i, (p, _, src) in enumerate(pgs):
                sl = jax.lax.dynamic_slice_in_dim(
                    new_p, int(offs[i]), meta["sizes"][i]).reshape(
                        p._data.shape).astype(src._data.dtype)
                self._commit(p, src, sl)

    def _fused_spill(self, key):
        """Write per-param slices of a group's flat state back into
        self._accumulators and drop the flat buffers."""
        meta = self._fused_parts.pop(key, None)
        if meta is None:
            return
        by_id = {id(p): p for p in self._parameter_list}
        for name, flat in zip(self._fused_state_names(), meta["states"]):
            store = self._accumulators[name]
            for i, pid in enumerate(meta["ids"]):
                p = by_id.get(pid)
                if p is None:
                    continue
                arr = flat._data[meta["offs"][i]:
                                 meta["offs"][i] + meta["sizes"][i]]
                t = Tensor(arr.reshape(p._data.shape), _internal=True)
                t.persistable = True
                store[pid] = t

    def _fused_acc_slice(self, name, p):
        """Per-param view of a flat fused state (for state_dict)."""
        sn = self._fused_state_names()
        if name not in sn:
            return None
        idx = sn.index(name)
        for meta in self._fused_parts.values():
            if id(p) in meta["ids"]:
                i = meta["ids"].index(id(p))
                arr = meta["states"][idx]._data[
                    meta["offs"][i]: meta["offs"][i] + meta["sizes"][i]]
                t = Tensor(arr.reshape(p._data.shape), _internal=True)
                t.persistable = True
                return t
        return None

    # ------------------------------------------------- scanned-step interop
    # The scan-over-layers donated train step (paddle_tpu/train) runs the
    # update FUNCTIONALLY: it owns stacked param/moment arrays and applies
    # `_fused_update` per leaf inside one jitted program. These hooks keep
    # THIS object the checkpoint truth: the step seeds its state from the
    # accumulators and writes the post-step slices back before state_dict.

    def functional_update(self):
        """(state_names, update_fn) for the pure fused update. update_fn
        (p32, g32, states, lr, wd, t) -> (new_p32, new_states) is
        elementwise, so it applies to stacked [nl, ...] leaves unchanged."""
        if not self._FUSABLE:
            raise ValueError(
                f"{type(self).__name__} has no pure fused update (per-tensor "
                "trust ratios etc.); the scanned train step cannot fuse it")
        return list(self._fused_state_names()), self._fused_update

    def get_state_array(self, name, p):
        """Current accumulator array for (state name, param) — from the
        per-param store or a fused flat slice — or None if not yet created."""
        t = self._accumulators.get(name, {}).get(id(p))
        if t is None and self._fused_parts:
            t = self._fused_acc_slice(name, p)
        return None if t is None else t._data

    def set_state_array(self, name, p, arr):
        """Adopt `arr` as the accumulator for (name, param). Any fused flat
        buffers are spilled first so per-param accumulators are the truth."""
        for key in list(self._fused_parts):
            self._fused_spill(key)
        t = Tensor(jnp.asarray(arr), _internal=True)
        t.persistable = True
        self._accumulators[name][id(p)] = t

    def set_master_array(self, p, arr):
        t = Tensor(jnp.asarray(arr, jnp.float32), _internal=True)
        t.persistable = True
        self._master_weights[id(p)] = t

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        """Static-graph-style convenience: backward already run via loss.backward()
        in dygraph; here minimize = backward + step (ref Optimizer.minimize)."""
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list]

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    # ------------------------------------------------------------------ ckpt

    def _param_keys(self):
        """Stable per-param checkpoint keys: the reference keys accumulators by
        parameter NAME (`<param_name>_moment1_0`), so state survives parameter
        lists built in a different order. Unnamed/duplicate names fall back to
        positional keys."""
        keys, seen = [], set()
        for i, p in enumerate(self._parameter_list):
            k = getattr(p, "name", "") or f"param_{i}"
            if k in seen:
                k = f"{k}__{i}"
            seen.add(k)
            keys.append(k)
        return keys

    def state_dict(self):
        sd = {}
        pkeys = self._param_keys()
        for name, store in self._accumulators.items():
            for pk, p in zip(pkeys, self._parameter_list):
                if id(p) in store:
                    sd[f"{pk}_{name}_0"] = store[id(p)]
        # fused flat states: emit per-param slices (checkpoint format parity)
        if self._fused_parts:
            for name in self._fused_state_names():
                for pk, p in zip(pkeys, self._parameter_list):
                    t = self._fused_acc_slice(name, p)
                    if t is not None:
                        sd[f"{pk}_{name}_0"] = t
        for pk, p in zip(pkeys, self._parameter_list):
            if id(p) in self._master_weights:
                sd[f"{pk}_master_0"] = self._master_weights[id(p)]
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        sd["global_step"] = self._global_step
        # manifest of per-param key prefixes in parameter-list order: lets load
        # align state positionally when auto-generated names differ between the
        # saving and loading process (the name counter is construction-order
        # global, so any extra Layer built first shifts every name)
        sd["__param_keys__"] = pkeys
        return sd

    def set_state_dict(self, state_dict):
        # accumulator names are parsed out of the checkpoint keys, so loading
        # into a freshly built optimizer (no accumulators yet) works
        self._fused_parts.clear()   # truth moves back to per-param accumulators
        pkeys = self._param_keys()

        def as_tensor(v):
            arr = v._data if isinstance(v, Tensor) else jnp.asarray(v)
            t = Tensor(arr, _internal=True)
            t.persistable = True
            return t

        def store(p, name, v):
            if name == "master":
                self._master_weights[id(p)] = as_tensor(v)
            else:
                self._accumulators[name][id(p)] = as_tensor(v)

        saved_keys = state_dict.get("__param_keys__")
        if saved_keys is None and not any(
                k.startswith(f"{pk}_") and k.endswith("_0")
                for pk in pkeys for k in state_dict):
            # legacy positional f"{name}_{i}" keys (round-1 checkpoints)
            for i, p in enumerate(self._parameter_list):
                for key, v in state_dict.items():
                    if key in ("LR_Scheduler", "global_step"):
                        continue
                    if key == f"master_{i}":
                        self._master_weights[id(p)] = as_tensor(v)
                    elif key.endswith(f"_{i}"):
                        name = key[: -(len(str(i)) + 1)]
                        self._accumulators[name][id(p)] = as_tensor(v)
        else:
            # group saved entries per param key; longest-prefix match so a key
            # that is a prefix of another ('w' vs 'w__1') can't steal entries
            groups = saved_keys if saved_keys is not None else pkeys
            by_param = {pk: {} for pk in groups}
            ordered = sorted(by_param, key=len, reverse=True)
            for key, v in state_dict.items():
                if key in ("LR_Scheduler", "global_step", "__param_keys__") \
                        or not key.endswith("_0"):
                    continue
                for pk in ordered:
                    if key.startswith(f"{pk}_"):
                        by_param[pk][key[len(pk) + 1:-2]] = v
                        break
            for i, (pk, p) in enumerate(zip(pkeys, self._parameter_list)):
                entries = by_param.get(pk)
                if not entries and saved_keys is not None \
                        and i < len(saved_keys):
                    # names differ between save/load: align positionally via
                    # the manifest order
                    entries = by_param.get(saved_keys[i], {})
                for name, v in (entries or {}).items():
                    store(p, name, v)
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate,
                                                       LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        self._global_step = int(state_dict.get("global_step", 0))

    load_state_dict = set_state_dict
