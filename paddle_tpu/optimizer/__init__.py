"""paddle.optimizer (ref: `python/paddle/optimizer/__init__.py`)."""
from paddle_tpu.optimizer.optimizer import Optimizer  # noqa: F401
from paddle_tpu.optimizer.optimizers import (  # noqa: F401
    SGD, Momentum, Adam, AdamW, Adagrad, Adamax, Adadelta, RMSProp, Lamb,
    LarsMomentum,
)
from paddle_tpu.optimizer import lr  # noqa: F401
