"""``paddle.onnx`` — export Layers to ONNX (ref `python/paddle/onnx/export.py`,
which delegates to paddle2onnx; here the jaxpr->ONNX emitter is in-tree, see
`export.py`; `runtime.py` is a numpy evaluator used for artifact validation)."""
from paddle_tpu.onnx.export import export  # noqa: F401
from paddle_tpu.onnx import runtime  # noqa: F401
