"""ONNX export — jaxpr → ONNX graph conversion.

The reference delegates `paddle.onnx.export` to the external paddle2onnx
package (`python/paddle/onnx/export.py:28`, which walks the static Program).
The TPU-native equivalent walks the *jaxpr* of the layer's traced forward:
parameters become initializers, each lax primitive maps to ONNX node(s), and
the ModelProto is serialized through the in-tree schema (`onnx.proto`,
official field numbers, so standard runtimes can load the artifact).

Covered primitive set: the elementwise/matmul/conv/pool/reduce/shape ops that
eval-mode vision and transformer blocks trace to. `dot_general` always lowers
to Einsum (exact for every contraction), convs to Conv, `reduce_window` max /
add to MaxPool / AveragePool.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.extend.core import Literal as _Literal
except ImportError:  # older/newer jax layouts
    from jax._src.core import Literal as _Literal

from paddle_tpu.onnx import onnx_pb2 as pb

_DTYPE = {
    np.dtype(np.float32): pb.TensorProto.FLOAT,
    np.dtype(np.float64): pb.TensorProto.DOUBLE,
    np.dtype(np.int32): pb.TensorProto.INT32,
    np.dtype(np.int64): pb.TensorProto.INT64,
    np.dtype(np.bool_): pb.TensorProto.BOOL,
    np.dtype(np.uint8): pb.TensorProto.UINT8,
    np.dtype(np.int8): pb.TensorProto.INT8,
    np.dtype(np.float16): pb.TensorProto.FLOAT16,
}


def _np_dtype(d):
    d = np.dtype(d) if not str(d).startswith("bfloat") else np.dtype(np.float32)
    return d


def _tensor_proto(name, arr):
    arr = np.asarray(arr)
    if arr.dtype == jnp.bfloat16:
        arr = arr.astype(np.float32)
    t = pb.TensorProto()
    t.name = name
    t.dims.extend(arr.shape)
    t.data_type = _DTYPE[np.dtype(arr.dtype)]
    t.raw_data = np.ascontiguousarray(arr).tobytes()
    return t


def _value_info(name, shape, dtype):
    vi = pb.ValueInfoProto()
    vi.name = name
    vi.type.tensor_type.elem_type = _DTYPE[_np_dtype(dtype)]
    for d in shape:
        vi.type.tensor_type.shape.dim.add().dim_value = int(d)
    return vi


class _Emitter:
    def __init__(self):
        self.nodes = []
        self.initializers = {}
        self._n = 0

    def fresh(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers[name] = _tensor_proto(name, arr)
        return name

    def node(self, op, inputs, n_out=1, name=None, **attrs):
        nd = pb.NodeProto()
        nd.op_type = op
        nd.name = name or self.fresh(op.lower())
        nd.input.extend(inputs)
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        nd.output.extend(outs)
        for k, v in attrs.items():
            a = nd.attribute.add()
            a.name = k
            if isinstance(v, float):
                a.f = v
                a.type = pb.AttributeProto.FLOAT
            elif isinstance(v, bool) or isinstance(v, (int, np.integer)):
                a.i = int(v)
                a.type = pb.AttributeProto.INT
            elif isinstance(v, str):
                a.s = v.encode()
                a.type = pb.AttributeProto.STRING
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, np.integer)) for x in v):
                a.ints.extend(int(x) for x in v)
                a.type = pb.AttributeProto.INTS
            else:
                raise TypeError(f"attr {k}={v!r}")
        self.nodes.append(nd)
        return outs[0] if n_out == 1 else outs


_UNARY = {
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "abs": "Abs", "neg": "Neg", "sign": "Sign",
    "floor": "Floor", "ceil": "Ceil", "round_nearest_even": "Round",
    "erf": "Erf", "sin": "Sin", "cos": "Cos", "not": "Not",
}
_BINARY = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div", "max": "Max",
    "min": "Min", "pow": "Pow",
    "gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual",
    "le": "LessOrEqual", "eq": "Equal", "and": "And", "or": "Or",
    "xor": "Xor",
}
_INLINE = {"jit", "pjit", "closed_call", "core_call", "custom_jvp_call",
           "custom_vjp_call", "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
           "remat", "checkpoint", "remat2", "custom_lin"}


def _inner_closed_jaxpr(eqn):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in eqn.params:
            cj = eqn.params[key]
            return cj
    raise NotImplementedError(
        f"cannot inline {eqn.primitive.name}: params {list(eqn.params)}")


def _einsum_eq(dn, lhs_ndim, rhs_ndim):
    (lc, rc), (lb, rb) = dn
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    lhs = [None] * lhs_ndim
    rhs = [None] * rhs_ndim
    out = []
    for i, j in zip(lb, rb):
        c = next(letters)
        lhs[i] = rhs[j] = c
        out.append(c)
    for i, j in zip(lc, rc):
        c = next(letters)
        lhs[i] = rhs[j] = c
    for i in range(lhs_ndim):
        if lhs[i] is None:
            lhs[i] = next(letters)
            out.append(lhs[i])
    for j in range(rhs_ndim):
        if rhs[j] is None:
            rhs[j] = next(letters)
            out.append(rhs[j])
    return f"{''.join(lhs)},{''.join(rhs)}->{''.join(out)}"


def _convert_eqn(eqn, env, em):
    prim = eqn.primitive.name
    ins = []
    for v in eqn.invars:
        if isinstance(v, _Literal):
            ins.append(em.const(np.asarray(v.val), "lit"))
        else:
            ins.append(env[v])

    def out(name_or_names):
        names = name_or_names if isinstance(name_or_names, list) \
            else [name_or_names]
        for var, nm in zip(eqn.outvars, names):
            env[var] = nm

    if prim in _INLINE:
        cj = _inner_closed_jaxpr(eqn)
        jx = cj.jaxpr if hasattr(cj, "jaxpr") else cj
        consts = list(getattr(cj, "consts", []))
        inner_env = {}
        cvars = list(jx.constvars)
        for cv, cval in zip(cvars, consts):
            inner_env[cv] = em.const(np.asarray(cval), "cv")
        for iv, nm in zip(jx.invars, ins[len(ins) - len(jx.invars):]):
            inner_env[iv] = nm
        for inner_eqn in jx.eqns:
            _convert_eqn(inner_eqn, inner_env, em)
        names = []
        for ov in jx.outvars:
            if isinstance(ov, _Literal):
                names.append(em.const(np.asarray(ov.val), "lit"))
            else:
                names.append(inner_env[ov])
        out(names)
        return

    if prim in _UNARY:
        out(em.node(_UNARY[prim], [ins[0]]))
    elif prim == "is_finite":
        # finite = not (isnan or isinf)
        bad = em.node("Or", [em.node("IsNaN", [ins[0]]),
                             em.node("IsInf", [ins[0]])])
        out(em.node("Not", [bad]))
    elif prim == "rem":
        # lax.rem is truncated (C-style) remainder -> Mod with fmod=1
        out(em.node("Mod", ins, fmod=1))
    elif prim == "ne":
        out(em.node("Not", [em.node("Equal", ins)]))
    elif prim == "rsqrt":
        out(em.node("Reciprocal", [em.node("Sqrt", [ins[0]])]))
    elif prim == "square":
        out(em.node("Mul", [ins[0], ins[0]]))
    elif prim == "integer_pow":
        e = em.const(np.asarray(float(eqn.params["y"]), np.float32))
        out(em.node("Pow", [ins[0], e]))
    elif prim in _BINARY:
        out(em.node(_BINARY[prim], ins))
    elif prim == "select_n":
        if len(ins) != 3:
            raise NotImplementedError("select_n with >2 cases")
        # select_n(pred, on_false, on_true) -> Where(pred, on_true, on_false)
        out(em.node("Where", [ins[0], ins[2], ins[1]]))
    elif prim == "stop_gradient" or prim == "copy":
        out(em.node("Identity", [ins[0]]))
    elif prim == "convert_element_type":
        to = _DTYPE[_np_dtype(eqn.params["new_dtype"])]
        out(em.node("Cast", [ins[0]], to=int(to)))
    elif prim == "reshape":
        shape = em.const(np.asarray(eqn.params["new_sizes"], np.int64))
        out(em.node("Reshape", [ins[0], shape]))
    elif prim == "transpose":
        out(em.node("Transpose", [ins[0]], perm=list(eqn.params["permutation"])))
    elif prim == "broadcast_in_dim":
        shape = eqn.params["shape"]
        bdims = eqn.params["broadcast_dimensions"]
        # reshape input into rank-len(shape) with 1s, then Expand
        in_shape = eqn.invars[0].aval.shape
        inter = [1] * len(shape)
        for src, dst in enumerate(bdims):
            inter[dst] = in_shape[src]
        r = em.node("Reshape",
                    [ins[0], em.const(np.asarray(inter, np.int64))])
        out(em.node("Expand", [r, em.const(np.asarray(shape, np.int64))]))
    elif prim in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
                  "argmax", "argmin"):
        axes = list(eqn.params["axes"]) if "axes" in eqn.params else \
            [eqn.params["axis"]]
        if prim == "reduce_sum":
            out(em.node("ReduceSum",
                        [ins[0], em.const(np.asarray(axes, np.int64))],
                        keepdims=0))
        elif prim in ("reduce_max", "reduce_min", "reduce_prod"):
            op = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
                  "reduce_prod": "ReduceProd"}[prim]
            out(em.node(op, [ins[0]], axes=axes, keepdims=0))
        else:
            op = "ArgMax" if prim == "argmax" else "ArgMin"
            res = em.node(op, [ins[0]], axis=axes[0], keepdims=0)
            # ONNX Arg* always emits INT64; the jaxpr aval may be int32
            want = _DTYPE[_np_dtype(eqn.outvars[0].aval.dtype)]
            if want != pb.TensorProto.INT64:
                res = em.node("Cast", [res], to=int(want))
            out(res)
    elif prim == "concatenate":
        out(em.node("Concat", ins, axis=int(eqn.params["dimension"])))
    elif prim == "pad":
        lo_hi = eqn.params["padding_config"]
        if any(p[2] != 0 for p in lo_hi):
            raise NotImplementedError("interior pad")
        pads = [p[0] for p in lo_hi] + [p[1] for p in lo_hi]
        out(em.node("Pad", [ins[0],
                            em.const(np.asarray(pads, np.int64)), ins[1]]))
    elif prim == "slice":
        starts = list(eqn.params["start_indices"])
        ends = list(eqn.params["limit_indices"])
        steps = list(eqn.params["strides"] or [1] * len(starts))
        axes = list(range(len(starts)))
        out(em.node("Slice", [
            ins[0], em.const(np.asarray(starts, np.int64)),
            em.const(np.asarray(ends, np.int64)),
            em.const(np.asarray(axes, np.int64)),
            em.const(np.asarray(steps, np.int64))]))
    elif prim == "rev":
        # Reverse via Slice with negative steps
        dims = list(eqn.params["dimensions"])
        shape = eqn.invars[0].aval.shape
        starts = [shape[d] - 1 for d in dims]
        ends = [-(shape[d] + 1) for d in dims]
        steps = [-1] * len(dims)
        out(em.node("Slice", [
            ins[0], em.const(np.asarray(starts, np.int64)),
            em.const(np.asarray(ends, np.int64)),
            em.const(np.asarray(dims, np.int64)),
            em.const(np.asarray(steps, np.int64))]))
    elif prim == "dot_general":
        eq = _einsum_eq(eqn.params["dimension_numbers"],
                        len(eqn.invars[0].aval.shape),
                        len(eqn.invars[1].aval.shape))
        out(em.node("Einsum", ins, equation=eq))
    elif prim == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        if (dn.lhs_spec != tuple(range(len(dn.lhs_spec)))
                or dn.rhs_spec != tuple(range(len(dn.rhs_spec)))
                or dn.out_spec != tuple(range(len(dn.out_spec)))):
            raise NotImplementedError(f"conv layout {dn}")
        if any(d != 1 for d in eqn.params["lhs_dilation"]):
            raise NotImplementedError("transposed conv export")
        pads_lohi = eqn.params["padding"]
        pads = [p[0] for p in pads_lohi] + [p[1] for p in pads_lohi]
        out(em.node("Conv", ins,
                    strides=list(eqn.params["window_strides"]),
                    pads=pads,
                    dilations=list(eqn.params["rhs_dilation"]),
                    group=int(eqn.params["feature_group_count"])))
    elif prim in ("reduce_window_max", "reduce_window_sum"):
        wd = list(eqn.params["window_dimensions"])
        ws = list(eqn.params["window_strides"])
        pad_cfg = eqn.params["padding"]
        if wd[0] != 1 or wd[1] != 1:
            raise NotImplementedError(f"pool window {wd}")
        pads = ([p[0] for p in pad_cfg[2:]] + [p[1] for p in pad_cfg[2:]])
        kernel = wd[2:]
        strides = ws[2:]
        if prim == "reduce_window_max":
            out(em.node("MaxPool", [ins[0]], kernel_shape=kernel,
                        strides=strides, pads=pads))
        else:
            avg = em.node("AveragePool", [ins[0]], kernel_shape=kernel,
                          strides=strides, pads=pads, count_include_pad=1)
            scale = em.const(np.asarray(float(np.prod(kernel)), np.float32))
            out(em.node("Mul", [avg, scale]))
    elif prim == "iota":
        shape = eqn.params["shape"]
        dim = eqn.params["dimension"]
        dt = _np_dtype(eqn.params["dtype"])
        rng = np.arange(shape[dim], dtype=dt)
        reps = [1] * len(shape)
        view = [1] * len(shape)
        view[dim] = shape[dim]
        arr = np.broadcast_to(rng.reshape(view), shape)
        out(em.const(np.ascontiguousarray(arr), "iota"))
    elif prim == "gather":
        # only embedding-style gathers: one collapsed dim, indices over axis 0
        gd = eqn.params["dimension_numbers"]
        if (gd.collapsed_slice_dims == (0,) and gd.start_index_map == (0,)):
            idx = ins[1]
            sq = em.node("Squeeze",
                         [idx, em.const(np.asarray([-1], np.int64))])
            out(em.node("Gather", [ins[0], sq], axis=0))
        else:
            raise NotImplementedError(f"gather {gd}")
    else:
        raise NotImplementedError(
            f"ONNX export: unsupported primitive '{prim}' "
            f"(params {list(eqn.params)})")


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export an eval-mode Layer to an ONNX file (ref paddle.onnx.export).

    input_spec: list of paddle.static.InputSpec-likes, Tensors, or shape
    tuples. Returns the path written.
    """
    from paddle_tpu.core.autograd import no_grad
    from paddle_tpu.core.tensor import Tensor

    if input_spec is None:
        raise ValueError("input_spec is required")
    if not str(path).endswith(".onnx"):
        path = str(path) + ".onnx"

    specs = []
    for s in input_spec:
        if isinstance(s, Tensor):
            specs.append(jax.ShapeDtypeStruct(tuple(s.shape), s._data.dtype))
        elif hasattr(s, "shape"):
            specs.append(jax.ShapeDtypeStruct(
                tuple(int(d) for d in s.shape),
                np.dtype(getattr(s, "dtype", "float32") or "float32")))
        else:
            specs.append(jax.ShapeDtypeStruct(tuple(s), np.float32))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:

        def pure(*arrs):
            with no_grad():
                outs = layer(*[Tensor(a, _internal=True) for a in arrs])
            if isinstance(outs, (tuple, list)):
                return tuple(o._data for o in outs if isinstance(o, Tensor))
            return (outs._data,)

        closed = jax.make_jaxpr(pure)(*specs)
        jx = closed.jaxpr

        em = _Emitter()
        env = {}
        input_names = []
        for i, (iv, spec) in enumerate(zip(jx.invars, specs)):
            nm = f"input_{i}"
            env[iv] = nm
            input_names.append(nm)
        for cv, cval in zip(jx.constvars, closed.consts):
            env[cv] = em.const(np.asarray(cval), "param")
        for eqn in jx.eqns:
            _convert_eqn(eqn, env, em)

        graph = pb.GraphProto()
        graph.name = type(layer).__name__
        graph.node.extend(em.nodes)
        graph.initializer.extend(em.initializers.values())
        for nm, spec in zip(input_names, specs):
            graph.input.append(_value_info(nm, spec.shape, spec.dtype))
        for i, ov in enumerate(jx.outvars):
            nm = env[ov] if not isinstance(ov, _Literal) else \
                em.const(np.asarray(ov.val), "out")
            # ONNX requires distinct graph output entries
            vi = _value_info(f"output_{i}", ov.aval.shape, ov.aval.dtype)
            graph.node.append(pb.NodeProto(op_type="Identity", input=[nm],
                                           output=[f"output_{i}"],
                                           name=em.fresh("out")))
            graph.output.append(vi)

        model = pb.ModelProto()
        model.ir_version = 7
        model.producer_name = "paddle_tpu"
        model.graph.CopyFrom(graph)
        ops = model.opset_import.add()
        ops.domain = ""
        ops.version = opset_version
        with open(path, "wb") as f:
            f.write(model.SerializeToString())
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()
    return path