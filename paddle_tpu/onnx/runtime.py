"""Minimal numpy evaluator for the ONNX subset this exporter emits.

Serves two roles: (1) the export tests execute the .onnx artifact and assert
numeric parity with the live Layer — end-to-end validation that the emitted
graph is semantically correct, not just well-formed; (2) a dependency-free way
to smoke-run exported models where no ONNX runtime is installed (the inference
tower's predictor covers the production path).
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.onnx import onnx_pb2 as pb

_NP_OF = {
    pb.TensorProto.FLOAT: np.float32, pb.TensorProto.DOUBLE: np.float64,
    pb.TensorProto.INT32: np.int32, pb.TensorProto.INT64: np.int64,
    pb.TensorProto.BOOL: np.bool_, pb.TensorProto.UINT8: np.uint8,
    pb.TensorProto.INT8: np.int8, pb.TensorProto.FLOAT16: np.float16,
}


def load(path):
    m = pb.ModelProto()
    with open(path, "rb") as f:
        m.ParseFromString(f.read())
    return m


def _tensor_value(t):
    dt = _NP_OF[t.data_type]
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=dt)
    elif t.float_data:
        arr = np.asarray(t.float_data, dt)
    elif t.int64_data:
        arr = np.asarray(t.int64_data, dt)
    else:
        arr = np.asarray(t.int32_data, dt)
    return arr.reshape(tuple(t.dims))


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == pb.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == pb.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == pb.AttributeProto.INTS:
            out[a.name] = list(a.ints)
        elif a.type == pb.AttributeProto.FLOATS:
            out[a.name] = list(a.floats)
    return out


def _pool2d(x, kernel, strides, pads, mode):
    n, c, h, w = x.shape
    ph0, pw0, ph1, pw1 = (pads + [0] * 4)[:4] if pads else (0, 0, 0, 0)
    fill = -np.inf if mode == "max" else 0.0
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)),
                constant_values=fill)
    kh, kw = kernel
    sh, sw = strides
    oh = (xp.shape[2] - kh) // sh + 1
    ow = (xp.shape[3] - kw) // sw + 1
    out = np.empty((n, c, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            out[:, :, i, j] = win.max((2, 3)) if mode == "max" else \
                win.mean((2, 3))
    return out


def _conv2d(x, w, b, strides, pads, dilations, group):
    n, cin, h, wd = x.shape
    cout, cin_g, kh, kw = w.shape
    ph0, pw0, ph1, pw1 = (pads + [0] * 4)[:4] if pads else (0, 0, 0, 0)
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    dh, dw = dilations or (1, 1)
    sh, sw = strides or (1, 1)
    ekh, ekw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    oh = (xp.shape[2] - ekh) // sh + 1
    ow = (xp.shape[3] - ekw) // sw + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    cpg_out = cout // group
    for g in range(group):
        xs = xp[:, g * cin_g:(g + 1) * cin_g]
        ws = w[g * cpg_out:(g + 1) * cpg_out]
        for i in range(oh):
            for j in range(ow):
                win = xs[:, :, i * sh:i * sh + ekh:dh, j * sw:j * sw + ekw:dw]
                out[:, g * cpg_out:(g + 1) * cpg_out, i, j] = np.einsum(
                    "nchw,ochw->no", win, ws)
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out.astype(x.dtype)


def run(model, inputs):
    """Execute the graph on numpy inputs (dict name->array or list in graph
    input order). Returns list of outputs."""
    g = model.graph
    env = {t.name: _tensor_value(t) for t in g.initializer}
    if isinstance(inputs, dict):
        env.update({k: np.asarray(v) for k, v in inputs.items()})
    else:
        for vi, arr in zip(g.input, inputs):
            env[vi.name] = np.asarray(arr)

    for node in g.node:
        a = _attrs(node)
        ins = [env[n] for n in node.input if n]
        op = node.op_type
        if op == "Identity":
            res = ins[0]
        elif op == "Add":
            res = ins[0] + ins[1]
        elif op == "Sub":
            res = ins[0] - ins[1]
        elif op == "Mul":
            res = ins[0] * ins[1]
        elif op == "Div":
            res = ins[0] / ins[1]
        elif op == "Max":
            res = np.maximum(ins[0], ins[1])
        elif op == "Min":
            res = np.minimum(ins[0], ins[1])
        elif op == "Pow":
            res = ins[0] ** ins[1]
        elif op == "Mod":
            res = np.fmod(ins[0], ins[1]) if a.get("fmod") else \
                np.mod(ins[0], ins[1])
        elif op == "Greater":
            res = ins[0] > ins[1]
        elif op == "Less":
            res = ins[0] < ins[1]
        elif op == "GreaterOrEqual":
            res = ins[0] >= ins[1]
        elif op == "LessOrEqual":
            res = ins[0] <= ins[1]
        elif op == "Equal":
            res = ins[0] == ins[1]
        elif op == "And":
            res = np.logical_and(ins[0], ins[1])
        elif op == "Or":
            res = np.logical_or(ins[0], ins[1])
        elif op == "Xor":
            res = np.logical_xor(ins[0], ins[1])
        elif op == "Not":
            res = np.logical_not(ins[0])
        elif op == "IsNaN":
            res = np.isnan(ins[0])
        elif op == "IsInf":
            res = np.isinf(ins[0])
        elif op == "Where":
            res = np.where(ins[0], ins[1], ins[2])
        elif op == "Exp":
            res = np.exp(ins[0])
        elif op == "Log":
            res = np.log(ins[0])
        elif op == "Tanh":
            res = np.tanh(ins[0])
        elif op == "Sigmoid":
            res = 1 / (1 + np.exp(-ins[0]))
        elif op == "Sqrt":
            res = np.sqrt(ins[0])
        elif op == "Reciprocal":
            res = 1 / ins[0]
        elif op == "Abs":
            res = np.abs(ins[0])
        elif op == "Neg":
            res = -ins[0]
        elif op == "Sign":
            res = np.sign(ins[0])
        elif op == "Floor":
            res = np.floor(ins[0])
        elif op == "Ceil":
            res = np.ceil(ins[0])
        elif op == "Round":
            res = np.round(ins[0])
        elif op == "Erf":
            from math import erf
            res = np.vectorize(erf)(ins[0]).astype(ins[0].dtype)
        elif op == "Sin":
            res = np.sin(ins[0])
        elif op == "Cos":
            res = np.cos(ins[0])
        elif op == "Cast":
            res = ins[0].astype(_NP_OF[a["to"]])
        elif op == "Reshape":
            res = ins[0].reshape(tuple(ins[1].astype(np.int64)))
        elif op == "Transpose":
            res = np.transpose(ins[0], a["perm"])
        elif op == "Expand":
            res = np.broadcast_to(ins[0], tuple(ins[1].astype(np.int64)))
        elif op == "Concat":
            res = np.concatenate(ins, axis=a["axis"])
        elif op == "Squeeze":
            res = np.squeeze(ins[0], axis=tuple(ins[1].astype(np.int64)))
        elif op == "Gather":
            res = np.take(ins[0], ins[1].astype(np.int64),
                          axis=a.get("axis", 0))
        elif op == "Slice":
            starts, ends, axes, steps = (x.astype(np.int64) for x in ins[1:5])
            sl = [slice(None)] * ins[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                sl[ax] = slice(int(s), None if e >= 2**62 else int(e), int(st))
            res = ins[0][tuple(sl)]
        elif op == "Pad":
            pads = ins[1].astype(np.int64)
            nd = ins[0].ndim
            widths = [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]
            cval = float(ins[2]) if len(ins) > 2 else 0.0
            res = np.pad(ins[0], widths, constant_values=cval)
        elif op == "ReduceSum":
            axes = tuple(ins[1].astype(np.int64)) if len(ins) > 1 else None
            res = ins[0].sum(axis=axes, keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ReduceMax", "ReduceMin", "ReduceProd", "ReduceMean"):
            fn = {"ReduceMax": np.max, "ReduceMin": np.min,
                  "ReduceProd": np.prod, "ReduceMean": np.mean}[op]
            res = fn(ins[0], axis=tuple(a["axes"]),
                     keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ArgMax", "ArgMin"):
            fn = np.argmax if op == "ArgMax" else np.argmin
            res = fn(ins[0], axis=a["axis"]).astype(np.int64)
            if a.get("keepdims", 1):
                res = np.expand_dims(res, a["axis"])
        elif op == "Einsum":
            res = np.einsum(a["equation"], *ins)
        elif op == "MaxPool":
            res = _pool2d(ins[0], a["kernel_shape"],
                          a.get("strides", [1, 1]), a.get("pads"), "max")
        elif op == "AveragePool":
            res = _pool2d(ins[0], a["kernel_shape"],
                          a.get("strides", [1, 1]), a.get("pads"), "avg")
        elif op == "Conv":
            b = ins[2] if len(ins) > 2 else None
            res = _conv2d(ins[0], ins[1], b, a.get("strides"), a.get("pads"),
                          a.get("dilations"), a.get("group", 1))
        else:
            raise NotImplementedError(f"runtime op {op}")
        outs = res if isinstance(res, tuple) else (res,)
        for nm, val in zip(node.output, outs):
            env[nm] = val

    return [env[vi.name] for vi in g.output]
